"""Sequence packing for MLM pretraining — fill every row, waste no MXU.

The corpus texts average ~18 tokens (`data/train.json`), so padding each to
`max_seq_len=128` would burn ~85% of the FLOPs on [PAD].  TPU-natively the
fix is *packing*: concatenate `[CLS] text [SEP]` segments back-to-back into
fixed `[N, S]` rows and carry a `segment_ids` channel; attention uses a
block-diagonal bias (`segment_bias`) so tokens never attend across text
boundaries, while every position in the row still trains the full 0..S-1
position-embedding table.  This has no reference twin — the reference never
pretrains (`/root/reference/single-gpu-cls.py:252-255` downloads pretrained
weights; this environment has no egress, so pretraining is built instead).

Shapes stay fully static: one (num_rows, S) int32 array per channel.
"""
from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pdnlp_tpu.data.collate import EncodedDataset
from pdnlp_tpu.data.tokenizer import WordPieceTokenizer


def pack_texts(
    tok: WordPieceTokenizer,
    texts: Sequence[str],
    max_seq_len: int = 128,
) -> Dict[str, np.ndarray]:
    """Greedy first-fit packing of tokenized texts into `[N, S]` rows.

    Returns `{"input_ids", "segment_ids"}`; `segment_ids` is 1-based per
    text within a row, 0 = padding.  A text longer than `S-2` tokens is
    truncated (same `longest_first` outcome as the fine-tune collator).
    """
    S = max_seq_len
    rows: List[List[int]] = []
    segs: List[List[int]] = []
    for text in texts:
        ids = tok.encode_ids(text, S)
        if not rows or len(rows[-1]) + len(ids) > S:
            rows.append([])
            segs.append([])
        seg = (segs[-1][-1] + 1) if segs[-1] else 1
        rows[-1].extend(ids)
        segs[-1].extend([seg] * len(ids))
    n = len(rows)
    input_ids = np.zeros((n, S), np.int32)
    segment_ids = np.zeros((n, S), np.int32)
    for i, (r, s) in enumerate(zip(rows, segs)):
        input_ids[i, : len(r)] = r
        segment_ids[i, : len(s)] = s
    return {"input_ids": input_ids, "segment_ids": segment_ids}


class PackedClassificationDataset(EncodedDataset):
    """Classification examples packed many-per-row — the fine-tune twin of
    :func:`pack_texts` (``--length_mode pack``).

    Quacks like :class:`~pdnlp_tpu.data.collate.EncodedDataset` (``arrays``
    / ``take`` / ``lengths``), so the loader, the device-resident pipeline,
    and the HBM-budget check all work unchanged — the unit simply becomes a
    packed ROW instead of an example.  Channels per row (all static):

    - ``input_ids`` ``[N, S]``: ``[CLS] text [SEP]`` segments back-to-back;
    - ``segment_ids`` ``[N, S]``: 1-based per segment, 0 = padding — feeds
      the block-diagonal ``segment_bias`` so examples never cross-attend;
    - ``attention_mask`` ``[N, S]``: ``segment_ids > 0``;
    - ``cls_positions`` ``[N, M]``: each segment's [CLS] token offset (the
      per-segment pooled-output gather in ``models.bert``);
    - ``label`` / ``example_weight`` ``[N, M]``: per-SEGMENT targets and
      weights (0 = empty slot), so the loss stays per-example, not per-row.

    Packing is computed ONCE (best-fit-decreasing, seeded by nothing —
    deterministic in the data): epochs shuffle packed *rows*, keeping the
    per-epoch step count and resume arithmetic exact.  What changes vs the
    host loader is batch composition only — which examples co-occur — never
    any example's own tokens, mask, or loss weight.
    """

    def __init__(self, encoded: EncodedDataset, max_segments: int = 16):
        S = encoded.seq_len
        M = int(max_segments)
        if M < 1:
            raise ValueError(f"pack_max_segments must be >= 1, got {M}")
        lengths = encoded.lengths()
        n = len(encoded)
        # best-fit-decreasing: for each example (longest first) pick the
        # open row with the LEAST free space that still fits it — O(n log n)
        # via a bisect-sorted (free, row) list; a row at the segment cap
        # closes.  Deterministic: ties break on row id (stable tuple order).
        order = np.argsort(-lengths, kind="stable")
        rows: List[List[int]] = []
        open_rows: List[tuple] = []  # sorted (free_tokens, row_id)
        for i in order.tolist():
            L = int(lengths[i])
            j = bisect.bisect_left(open_rows, (L, -1))
            if j < len(open_rows):
                free, rid = open_rows.pop(j)
                rows[rid].append(i)
                if len(rows[rid]) < M and free - L > 0:
                    bisect.insort(open_rows, (free - L, rid))
            else:
                rows.append([i])
                if M > 1 and S - L > 0:
                    bisect.insort(open_rows, (S - L, len(rows) - 1))
        N = len(rows)
        src_ids = encoded.arrays["input_ids"]
        src_lab = encoded.arrays["label"]
        input_ids = np.zeros((N, S), np.int32)
        segment_ids = np.zeros((N, S), np.int32)
        position_ids = np.zeros((N, S), np.int32)
        cls_pos = np.zeros((N, M), np.int32)
        label = np.zeros((N, M), np.int32)
        weight = np.zeros((N, M), np.float32)
        for r, members in enumerate(rows):
            off = 0
            for s, i in enumerate(members):
                L = int(lengths[i])
                input_ids[r, off: off + L] = src_ids[i, :L]
                segment_ids[r, off: off + L] = s + 1
                # positions restart per segment: each example sees exactly
                # the position embeddings its unpacked encoding would —
                # packed-vs-unpacked forward parity is exact, not modulo a
                # row-offset shift (tests/test_length.py pins it)
                position_ids[r, off: off + L] = np.arange(L, dtype=np.int32)
                cls_pos[r, s] = off
                label[r, s] = src_lab[i]
                weight[r, s] = 1.0
                off += L
        self.arrays = {
            "input_ids": input_ids,
            "segment_ids": segment_ids,
            "position_ids": position_ids,
            "attention_mask": (segment_ids > 0).astype(np.int32),
            "token_type_ids": np.zeros((N, S), np.int32),
            "cls_positions": cls_pos,
            "label": label,
            "example_weight": weight,
        }
        self.n = N
        self.seq_len = S
        self.max_segments = M
        self.num_examples = n

    def stats(self) -> Dict[str, float]:
        """Packing efficiency numbers for the bench smoke."""
        seg_counts = (self.arrays["example_weight"] > 0).sum(1)
        tokens_real = int(self.arrays["attention_mask"].sum())
        return {
            "rows": self.n,
            "examples": self.num_examples,
            "tokens_real": tokens_real,
            "fill_ratio": tokens_real / float(self.n * self.seq_len)
            if self.n else 0.0,
            "segments_per_row_mean": float(seg_counts.mean())
            if self.n else 0.0,
            "segments_per_row_max": int(seg_counts.max()) if self.n else 0,
        }


def pack_classification(encoded: EncodedDataset, max_segments: int = 16
                        ) -> PackedClassificationDataset:
    """Pack an encoded classification split into multi-example rows."""
    return PackedClassificationDataset(encoded, max_segments=max_segments)


def pack_id_lists(
    id_lists: Sequence[Sequence[int]],
    seq_len: int,
    rows: int,
    max_segments: int,
    pad_id: int = 0,
) -> Tuple[Dict[str, np.ndarray], List[Optional[Tuple[int, int]]]]:
    """Bin-pack ragged token-id lists into ONE fixed ``[rows, seq_len]``
    packed batch — the online-serving twin of
    :class:`PackedClassificationDataset` (same channel layout, so
    ``models.bert.classify`` and the pallas segment kernel consume it
    unchanged), minus the label/weight channels serving never has.

    The caller's order IS the priority order (the serve batcher sorts by
    remaining deadline slack, lowest first, so the most urgent requests
    close the earliest rows): placement is first-fit over the open rows in
    order, and a list that fits nowhere right now is *skipped* — it could
    not ride this batch anyway — while later, shorter lists may still fill
    the gaps it left.

    Returns ``(batch, placements)`` where ``placements[i]`` is the
    ``(row, slot)`` the ``i``-th list landed at, or ``None`` if it did not
    fit (the caller keeps it queued for the next batch).  ``batch`` always
    has the full ``rows`` x ``seq_len`` shape (unused rows stay padding)
    so the packed forward is one compiled program per ``(rows, seq_len)``
    — retrace-free by construction.
    """
    S, R, M = int(seq_len), int(rows), int(max_segments)
    if R < 1 or M < 1:
        raise ValueError(f"need rows >= 1 and max_segments >= 1, "
                         f"got rows={R} max_segments={M}")
    input_ids = np.full((R, S), pad_id, np.int32)
    segment_ids = np.zeros((R, S), np.int32)
    position_ids = np.zeros((R, S), np.int32)
    cls_pos = np.zeros((R, M), np.int32)
    used = [0] * R     # tokens occupied per row
    segs = [0] * R     # segments opened per row
    opened = 0         # rows touched so far (first-fit opens them in order)
    placements: List[Optional[Tuple[int, int]]] = []
    for ids in id_lists:
        L = len(ids)
        if L > S:
            raise ValueError(f"list of {L} tokens exceeds the {S}-token "
                             "pack width — truncate before packing")
        if L == 0:
            # an empty list would open a phantom segment whose
            # cls_positions entry aliases the NEXT segment's offset — its
            # caller would silently receive a neighbor's logits.  Callers
            # (serve submit paths) reject empties before packing.
            raise ValueError("empty id list cannot be packed — reject "
                             "empty requests before batch formation")
        row = next((r for r in range(opened)
                    if segs[r] < M and used[r] + L <= S), None)
        if row is None:
            if opened >= R:
                placements.append(None)  # full batch: ride the next one
                continue
            row = opened
            opened += 1
        off = used[row]
        input_ids[row, off: off + L] = np.asarray(ids, np.int32)
        segment_ids[row, off: off + L] = segs[row] + 1
        # positions restart per segment — exact embedding parity with the
        # request's own padded forward (the training packer's contract)
        position_ids[row, off: off + L] = np.arange(L, dtype=np.int32)
        cls_pos[row, segs[row]] = off
        placements.append((row, segs[row]))
        used[row] += L
        segs[row] += 1
    batch = {
        "input_ids": input_ids,
        "segment_ids": segment_ids,
        "position_ids": position_ids,
        "attention_mask": (segment_ids > 0).astype(np.int32),
        "token_type_ids": np.zeros((R, S), np.int32),
        "cls_positions": cls_pos,
    }
    return batch, placements


def segment_bias(segment_ids: np.ndarray, dtype=np.float32) -> np.ndarray:
    """`[B, S]` segment ids -> `[B, 1, S, S]` additive attention bias.

    0 where query and key share a (nonzero) segment, -1e9 elsewhere — the
    block-diagonal mask that keeps packed texts independent.  Pure
    arithmetic/broadcast ops so the same function traces under jit (jnp
    arrays) and runs on host numpy.

    This is the XLA FALLBACK materialization only: the routed default
    passes the raw ``segment_ids`` down (``models.bert`` ->
    ``ops.attention``) and the pallas flash kernel derives the mask
    in-VMEM from the IDs — the quadratic [B, 1, S, S] tensor never
    reaches HBM.  ``ops.attention.dot_product_attention`` calls this only
    when the XLA path executes; nothing upstream should.
    """
    q = segment_ids[:, :, None]
    k = segment_ids[:, None, :]
    same = ((q == k) & (q > 0)).astype(dtype)
    return ((1.0 - same) * -1e9)[:, None, :, :]
