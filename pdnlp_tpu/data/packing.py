"""Sequence packing for MLM pretraining — fill every row, waste no MXU.

The corpus texts average ~18 tokens (`data/train.json`), so padding each to
`max_seq_len=128` would burn ~85% of the FLOPs on [PAD].  TPU-natively the
fix is *packing*: concatenate `[CLS] text [SEP]` segments back-to-back into
fixed `[N, S]` rows and carry a `segment_ids` channel; attention uses a
block-diagonal bias (`segment_bias`) so tokens never attend across text
boundaries, while every position in the row still trains the full 0..S-1
position-embedding table.  This has no reference twin — the reference never
pretrains (`/root/reference/single-gpu-cls.py:252-255` downloads pretrained
weights; this environment has no egress, so pretraining is built instead).

Shapes stay fully static: one (num_rows, S) int32 array per channel.
"""
from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pdnlp_tpu.data.collate import EncodedDataset
from pdnlp_tpu.data.tokenizer import WordPieceTokenizer


def pack_texts(
    tok: WordPieceTokenizer,
    texts: Sequence[str],
    max_seq_len: int = 128,
) -> Dict[str, np.ndarray]:
    """Greedy first-fit packing of tokenized texts into `[N, S]` rows.

    Returns `{"input_ids", "segment_ids"}`; `segment_ids` is 1-based per
    text within a row, 0 = padding.  A text longer than `S-2` tokens is
    truncated (same `longest_first` outcome as the fine-tune collator).
    """
    S = max_seq_len
    rows: List[List[int]] = []
    segs: List[List[int]] = []
    for text in texts:
        ids = tok.encode_ids(text, S)
        if not rows or len(rows[-1]) + len(ids) > S:
            rows.append([])
            segs.append([])
        seg = (segs[-1][-1] + 1) if segs[-1] else 1
        rows[-1].extend(ids)
        segs[-1].extend([seg] * len(ids))
    n = len(rows)
    input_ids = np.zeros((n, S), np.int32)
    segment_ids = np.zeros((n, S), np.int32)
    for i, (r, s) in enumerate(zip(rows, segs)):
        input_ids[i, : len(r)] = r
        segment_ids[i, : len(s)] = s
    return {"input_ids": input_ids, "segment_ids": segment_ids}


class _BfdPacker:
    """Best-fit-decreasing placement core: feed items longest first; each
    goes to the open row with the LEAST free space that still fits it —
    O(n log n) via a bisect-sorted (free, row) list; a row at the segment
    cap closes.  Deterministic: ties break on row id (stable tuple
    order).  ONE copy of the placement invariants, shared by the
    single-width packer and the multi-width seed/backfill passes."""

    def __init__(self, S: int, M: int):
        self.S, self.M = int(S), int(M)
        self.rows: List[List[int]] = []
        self._open: List[tuple] = []  # sorted (free_tokens, row_id)

    @property
    def has_open(self) -> bool:
        return bool(self._open)

    def place(self, i: int, L: int, open_new: bool = True) -> bool:
        """Place item ``i`` of ``L`` tokens; ``open_new=False`` restricts
        to existing open rows (the backfill pass never opens rows)."""
        j = bisect.bisect_left(self._open, (L, -1))
        if j < len(self._open):
            free, rid = self._open.pop(j)
            self.rows[rid].append(i)
            if len(self.rows[rid]) < self.M and free - L > 0:
                bisect.insort(self._open, (free - L, rid))
            return True
        if not open_new:
            return False
        self.rows.append([i])
        if self.M > 1 and self.S - L > 0:
            bisect.insort(self._open, (self.S - L, len(self.rows) - 1))
        return True


def _bfd_rows(lengths: np.ndarray, S: int, M: int) -> List[List[int]]:
    """Pack every item (longest first) via :class:`_BfdPacker`; returns
    rows of POSITIONS into ``lengths``."""
    packer = _BfdPacker(S, M)
    for i in np.argsort(-np.asarray(lengths), kind="stable").tolist():
        packer.place(i, int(lengths[i]))
    return packer.rows


def segment_cap(width: int, base_cap: int, base_width: int = 128) -> int:
    """Per-width segment capacity: ``--pack_max_segments`` is defined at
    the base (128-token, one kernel block) width and scales linearly with
    the row width, so a 512-wide packed row admits 4x the segments a
    128-wide one does — same expected density, per-width ``[N, M]``
    channel shapes stay bounded."""
    return max(1, int(base_cap) * int(width) // int(base_width))


class PackedClassificationDataset(EncodedDataset):
    """Classification examples packed many-per-row — the fine-tune twin of
    :func:`pack_texts` (``--length_mode pack``).

    Quacks like :class:`~pdnlp_tpu.data.collate.EncodedDataset` (``arrays``
    / ``take`` / ``lengths``), so the loader, the device-resident pipeline,
    and the HBM-budget check all work unchanged — the unit simply becomes a
    packed ROW instead of an example.  Channels per row (all static):

    - ``input_ids`` ``[N, S]``: ``[CLS] text [SEP]`` segments back-to-back;
    - ``segment_ids`` ``[N, S]``: 1-based per segment, 0 = padding — feeds
      the block-diagonal ``segment_bias`` so examples never cross-attend;
    - ``attention_mask`` ``[N, S]``: ``segment_ids > 0``;
    - ``cls_positions`` ``[N, M]``: each segment's [CLS] token offset (the
      per-segment pooled-output gather in ``models.bert``);
    - ``label`` / ``example_weight`` ``[N, M]``: per-SEGMENT targets and
      weights (0 = empty slot), so the loss stays per-example, not per-row.

    ``width`` overrides the row width (default: the encoding width) —
    the multi-width path (:class:`MultiWidthPackedDataset`) packs each
    length bucket at its own kernel-tiling width.  ``subset`` restricts
    packing to those encoded-example indices (the bucket's members);
    labels and tokens are still read from the full encoded split.
    ``rows`` (lists of encoded-example indices) bypasses the packer and
    assembles exactly those rows — the multi-width container computes
    its own backfilled packing and hands the rows here for assembly.

    Packing is computed ONCE (best-fit-decreasing, seeded by nothing —
    deterministic in the data): epochs shuffle packed *rows*, keeping the
    per-epoch step count and resume arithmetic exact.  What changes vs the
    host loader is batch composition only — which examples co-occur — never
    any example's own tokens, mask, or loss weight.
    """

    def __init__(self, encoded: EncodedDataset, max_segments: int = 16,
                 width: Optional[int] = None,
                 subset: Optional[Sequence[int]] = None,
                 rows: Optional[List[List[int]]] = None):
        S = int(width) if width else encoded.seq_len
        M = int(max_segments)
        if M < 1:
            raise ValueError(f"pack_max_segments must be >= 1, got {M}")
        all_len = encoded.lengths()
        if rows is None:
            members_idx = (np.arange(len(encoded), dtype=np.int64)
                           if subset is None
                           else np.asarray(subset, np.int64))
            lengths = all_len[members_idx]
            if len(members_idx) and int(lengths.max()) > S:
                raise ValueError(
                    f"cannot pack a {int(lengths.max())}-token example "
                    f"into {S}-wide rows — the packing width must cover "
                    "every member (partition by covering width first)")
            rows_pos = _bfd_rows(lengths, S, M)
            rows = [[int(members_idx[i]) for i in r] for r in rows_pos]
            n = len(members_idx)
        else:
            rows = [[int(i) for i in r] for r in rows]
            for r in rows:
                if len(r) > M:
                    raise ValueError(f"row carries {len(r)} segments, "
                                     f"cap is {M}")
                if int(all_len[r].sum()) > S:
                    raise ValueError("row overflows the packing width")
            n = sum(len(r) for r in rows)
        lengths = all_len  # assembly below indexes ORIGINAL example ids
        N = len(rows)
        src_ids = encoded.arrays["input_ids"]
        src_lab = encoded.arrays["label"]
        input_ids = np.zeros((N, S), np.int32)
        segment_ids = np.zeros((N, S), np.int32)
        position_ids = np.zeros((N, S), np.int32)
        cls_pos = np.zeros((N, M), np.int32)
        label = np.zeros((N, M), np.int32)
        weight = np.zeros((N, M), np.float32)
        source_rows: List[List[int]] = [list(r) for r in rows]
        for r, members in enumerate(rows):
            off = 0
            for s, orig in enumerate(members):
                L = int(lengths[orig])
                input_ids[r, off: off + L] = src_ids[orig, :L]
                segment_ids[r, off: off + L] = s + 1
                # positions restart per segment: each example sees exactly
                # the position embeddings its unpacked encoding would —
                # packed-vs-unpacked forward parity is exact, not modulo a
                # row-offset shift (tests/test_length.py pins it)
                position_ids[r, off: off + L] = np.arange(L, dtype=np.int32)
                cls_pos[r, s] = off
                label[r, s] = src_lab[orig]
                weight[r, s] = 1.0
                off += L
        self.arrays = {
            "input_ids": input_ids,
            "segment_ids": segment_ids,
            "position_ids": position_ids,
            "attention_mask": (segment_ids > 0).astype(np.int32),
            "token_type_ids": np.zeros((N, S), np.int32),
            "cls_positions": cls_pos,
            "label": label,
            "example_weight": weight,
        }
        self.n = N
        self.seq_len = S
        self.width = S
        self.max_segments = M
        self.num_examples = n
        #: per packed row, the ORIGINAL encoded-example indices riding it
        #: (coverage/parity tests and the multi-width container use it)
        self.source_rows = source_rows

    def stats(self) -> Dict[str, float]:
        """Packing efficiency numbers for the bench smoke."""
        seg_counts = (self.arrays["example_weight"] > 0).sum(1)
        tokens_real = int(self.arrays["attention_mask"].sum())
        return {
            "rows": self.n,
            "examples": self.num_examples,
            "tokens_real": tokens_real,
            "fill_ratio": tokens_real / float(self.n * self.seq_len)
            if self.n else 0.0,
            "segments_per_row_mean": float(seg_counts.mean())
            if self.n else 0.0,
            "segments_per_row_max": int(seg_counts.max()) if self.n else 0,
        }


def pack_classification(encoded: EncodedDataset, max_segments: int = 16
                        ) -> PackedClassificationDataset:
    """Pack an encoded classification split into multi-example rows."""
    return PackedClassificationDataset(encoded, max_segments=max_segments)


class MultiWidthPackedDataset:
    """The multi-width pack layout (``--length_mode pack`` with several
    kernel-tiling widths in ``--length_buckets``): each example lands in
    the SMALLEST covering width bucket and each bucket packs at its own
    width (one :class:`PackedClassificationDataset` per width, segment cap
    scaled by :func:`segment_cap`), so a long-document split does not pad
    its short tail up to the long width — short docs ride dense 128/256
    rows while the long ones pack 512/1024/2048 rows, all on the exact
    channel layout the segment-native flash kernel consumes.

    Packing runs WIDEST-FIRST with backfill: a width's rows are seeded by
    the examples that NEED it (covering width = this width) via
    best-fit-decreasing, then topped up from the still-unpacked shorter
    examples (longest first, same best-fit placement) — a 512-wide row
    holding one 300-token document backfills with ~200 tokens of short
    documents instead of padding.  Without backfill the per-row residue
    caps fill near the mean member length over the width (~0.75); with it
    the measured fill clears the 0.85 gate (``bench.py --longcontext``).

    Rows live in ONE global index space (width groups concatenated in
    ascending width order); batching rides the ordinary
    :class:`~pdnlp_tpu.data.sampler.LengthGroupedSampler` over
    :meth:`row_width_table` with the widths as the buckets — batches stay
    width-homogeneous, the compile count stays bounded at
    ``len(widths) x step-variants``, and the epoch structure is
    epoch-invariant, exactly the bucket-mode contract.  Not an
    :class:`~pdnlp_tpu.data.collate.EncodedDataset` (there is no single
    rectangular array), so the device-resident pipeline declines it and
    ``--pipeline auto`` falls back to prefetch — documented, measured in
    ``bench.py --longcontext``.
    """

    def __init__(self, encoded: EncodedDataset, widths: Sequence[int],
                 max_segments: int = 16, base_width: int = 128):
        ws = tuple(sorted(int(w) for w in set(widths)))
        if not ws:
            raise ValueError("need at least one packing width")
        lengths = encoded.lengths()
        if len(encoded) and int(lengths.max()) > ws[-1]:
            raise ValueError(
                f"longest example ({int(lengths.max())} tokens) exceeds "
                f"the largest packing width {ws[-1]} — include a covering "
                "width in --length_buckets")
        edges = np.asarray(ws, np.int64)
        member = edges[np.minimum(np.searchsorted(edges, lengths),
                                  len(edges) - 1)]
        # widest-first with backfill (class docstring): each width packs
        # its REQUIRED members, then draws from the shorter remainder
        remaining = {w: set(np.flatnonzero(member == w).tolist())
                     for w in ws}
        rows_by_width: Dict[int, List[List[int]]] = {}
        for w in reversed(ws):
            packer = _BfdPacker(w, segment_cap(w, max_segments, base_width))
            need = sorted(remaining[w], key=lambda i: (-lengths[i], i))
            remaining[w] = set()
            for i in need:                # seed: the width's own members
                packer.place(i, int(lengths[i]))
            pool = sorted((i for w2 in ws if w2 < w for i in remaining[w2]),
                          key=lambda i: (-lengths[i], i))
            for i in pool:                # backfill: no new rows opened
                if not packer.has_open:
                    break
                if packer.place(i, int(lengths[i]), open_new=False):
                    remaining[edges[np.searchsorted(edges,
                                                    lengths[i])]].discard(i)
            if packer.rows:
                rows_by_width[w] = packer.rows
        self.widths = ws
        self.groups: Dict[int, PackedClassificationDataset] = {}
        self._offsets: Dict[int, int] = {}
        off = 0
        for w in ws:
            if w not in rows_by_width:
                continue
            g = PackedClassificationDataset(
                encoded, max_segments=segment_cap(w, max_segments,
                                                  base_width),
                width=w, rows=rows_by_width[w])
            self.groups[w] = g
            self._offsets[w] = off
            off += g.n
        self.n = off
        self.seq_len = ws[-1]          # widest row (HBM-budget shape)
        self.num_examples = len(encoded)

    def __len__(self) -> int:
        return self.n

    def row_width_table(self) -> np.ndarray:
        """[n] row widths — the ``lengths`` input of the
        ``LengthGroupedSampler`` that batches this dataset (with
        ``buckets=self.widths`` the covering bucket IS the row's width)."""
        out = np.zeros((self.n,), np.int64)
        for w, g in self.groups.items():
            off = self._offsets[w]
            out[off: off + g.n] = w
        return out

    def lengths(self) -> np.ndarray:
        """Real token count per packed row (parity with EncodedDataset)."""
        out = np.zeros((self.n,), np.int64)
        for w, g in self.groups.items():
            off = self._offsets[w]
            out[off: off + g.n] = g.lengths()
        return out

    def take(self, indices: Sequence[int], pad_to: int = 0,
             seq_len: int = 0) -> Dict[str, np.ndarray]:
        """Assemble one width-homogeneous batch of packed rows.

        ``seq_len`` names the batch's width (the sampler supplies it);
        every index must belong to that width's group — the sampler
        guarantees it, and mixing widths is a hard error, not a pad."""
        w = int(seq_len) or self.seq_len
        if w not in self.groups:
            raise ValueError(f"no packed rows at width {w} "
                             f"(have {sorted(self.groups)})")
        off, g = self._offsets[w], self.groups[w]
        local = np.asarray(indices, np.int64) - off
        if len(local) and (local.min() < 0 or local.max() >= g.n):
            raise ValueError(
                f"batch mixes widths: indices outside the width-{w} group")
        return g.take(local, pad_to=pad_to)

    def stats(self) -> Dict[str, object]:
        """Per-width packing stats + the token-weighted aggregate fill."""
        per = {int(w): g.stats() for w, g in self.groups.items()}
        slots = sum(g.n * w for w, g in self.groups.items())
        real = sum(int(g.arrays["attention_mask"].sum())
                   for g in self.groups.values())
        return {"by_width": per,
                "rows": self.n,
                "examples": self.num_examples,
                "fill_ratio": real / float(slots) if slots else 0.0}


def pack_id_lists(
    id_lists: Sequence[Sequence[int]],
    seq_len: int,
    rows: int,
    max_segments: int,
    pad_id: int = 0,
) -> Tuple[Dict[str, np.ndarray], List[Optional[Tuple[int, int]]]]:
    """Bin-pack ragged token-id lists into ONE fixed ``[rows, seq_len]``
    packed batch — the online-serving twin of
    :class:`PackedClassificationDataset` (same channel layout, so
    ``models.bert.classify`` and the pallas segment kernel consume it
    unchanged), minus the label/weight channels serving never has.

    The caller's order IS the priority order (the serve batcher sorts by
    remaining deadline slack, lowest first, so the most urgent requests
    close the earliest rows): placement is first-fit over the open rows in
    order, and a list that fits nowhere right now is *skipped* — it could
    not ride this batch anyway — while later, shorter lists may still fill
    the gaps it left.

    Returns ``(batch, placements)`` where ``placements[i]`` is the
    ``(row, slot)`` the ``i``-th list landed at, or ``None`` if it did not
    fit (the caller keeps it queued for the next batch).  ``batch`` always
    has the full ``rows`` x ``seq_len`` shape (unused rows stay padding)
    so the packed forward is one compiled program per ``(rows, seq_len)``
    — retrace-free by construction.
    """
    S, R, M = int(seq_len), int(rows), int(max_segments)
    if R < 1 or M < 1:
        raise ValueError(f"need rows >= 1 and max_segments >= 1, "
                         f"got rows={R} max_segments={M}")
    input_ids = np.full((R, S), pad_id, np.int32)
    segment_ids = np.zeros((R, S), np.int32)
    position_ids = np.zeros((R, S), np.int32)
    cls_pos = np.zeros((R, M), np.int32)
    used = [0] * R     # tokens occupied per row
    segs = [0] * R     # segments opened per row
    opened = 0         # rows touched so far (first-fit opens them in order)
    placements: List[Optional[Tuple[int, int]]] = []
    for ids in id_lists:
        L = len(ids)
        if L > S:
            raise ValueError(f"list of {L} tokens exceeds the {S}-token "
                             "pack width — truncate before packing")
        if L == 0:
            # an empty list would open a phantom segment whose
            # cls_positions entry aliases the NEXT segment's offset — its
            # caller would silently receive a neighbor's logits.  Callers
            # (serve submit paths) reject empties before packing.
            raise ValueError("empty id list cannot be packed — reject "
                             "empty requests before batch formation")
        row = next((r for r in range(opened)
                    if segs[r] < M and used[r] + L <= S), None)
        if row is None:
            if opened >= R:
                placements.append(None)  # full batch: ride the next one
                continue
            row = opened
            opened += 1
        off = used[row]
        input_ids[row, off: off + L] = np.asarray(ids, np.int32)
        segment_ids[row, off: off + L] = segs[row] + 1
        # positions restart per segment — exact embedding parity with the
        # request's own padded forward (the training packer's contract)
        position_ids[row, off: off + L] = np.arange(L, dtype=np.int32)
        cls_pos[row, segs[row]] = off
        placements.append((row, segs[row]))
        used[row] += L
        segs[row] += 1
    batch = {
        "input_ids": input_ids,
        "segment_ids": segment_ids,
        "position_ids": position_ids,
        "attention_mask": (segment_ids > 0).astype(np.int32),
        "token_type_ids": np.zeros((R, S), np.int32),
        "cls_positions": cls_pos,
    }
    return batch, placements


def segment_bias(segment_ids: np.ndarray, dtype=np.float32) -> np.ndarray:
    """`[B, S]` segment ids -> `[B, 1, S, S]` additive attention bias.

    0 where query and key share a (nonzero) segment, -1e9 elsewhere — the
    block-diagonal mask that keeps packed texts independent.  Pure
    arithmetic/broadcast ops so the same function traces under jit (jnp
    arrays) and runs on host numpy.

    This is the XLA FALLBACK materialization only: the routed default
    passes the raw ``segment_ids`` down (``models.bert`` ->
    ``ops.attention``) and the pallas flash kernel derives the mask
    in-VMEM from the IDs — the quadratic [B, 1, S, S] tensor never
    reaches HBM.  ``ops.attention.dot_product_attention`` calls this only
    when the XLA path executes; nothing upstream should.
    """
    q = segment_ids[:, :, None]
    k = segment_ids[:, None, :]
    same = ((q == k) & (q > 0)).astype(dtype)
    return ((1.0 - same) * -1e9)[:, None, :, :]
