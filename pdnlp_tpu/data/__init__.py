from pdnlp_tpu.data.corpus import LABELS, label2id, id2label, load_data, split_data
from pdnlp_tpu.data.tokenizer import WordPieceTokenizer, build_vocab
from pdnlp_tpu.data.collate import Collator, EncodedDataset
from pdnlp_tpu.data.packing import (
    PackedClassificationDataset, pack_classification,
)
from pdnlp_tpu.data.sampler import (
    DistributedShardSampler, LengthGroupedSampler, parse_buckets,
    resolve_length_mode,
)
from pdnlp_tpu.data.loader import DataLoader
from pdnlp_tpu.data.pipeline import (
    DevicePrefetchPipeline, DeviceResidentPipeline, InputPipeline,
    SyncPipeline, build_pipeline,
)
