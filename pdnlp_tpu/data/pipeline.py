"""Input pipeline — how batches reach the device.

The reference hides input cost behind ``DataLoader(num_workers=2)``
subprocesses (``multi-gpu-distributed-cls.py:318``); this repo's loader
already overlaps *tokenization* with compute, but the upload itself — the
``put(batch)`` host->device transfer — sat inside the timed step loop,
serializing the device tunnel against dispatch.  Three modes behind one
interface (:func:`build_pipeline`) move it out:

- ``"resident"`` — the encoded split is uploaded to HBM ONCE,
  data-parallel-sharded on its row axis.  Per epoch, one tiny upload of the
  seeded permutation indices; per step, a jitted on-device gather assembles
  the batch from an on-device counter — steady-state per-step host->device
  transport is ZERO bytes.  The permutation reuses the loader's own
  :class:`DistributedShardSampler` chunks, so the batch stream (and every
  loss trace, resume fast-forward, and elastic test) is bitwise identical
  to the host loader's.  Default whenever the encoded split fits the
  ``--pipeline_hbm_mb`` budget (this corpus is ~14 MB at seq 128 — it
  always does), the run is single-process, and the loader carries an
  :class:`~pdnlp_tpu.data.collate.EncodedDataset` (a shuffling/augmenting
  *collator* has no frozen encoding to upload: resident mode is refused).
- ``"prefetch"`` — double-buffered host->device upload: a background
  worker ``put``s batch *k+1* while step *k* executes, with AT MOST ONE
  batch in flight (uploaded but not yet handed to the loop) — the tf.data
  prefetch the flat reference never had.  Fallback for corpora over
  budget, multi-process runs, and custom batch placements (sp/pp).
- ``"sync"`` — the reference behavior: upload inline in the step loop
  (kept for A/B measurement; ``bench.py --pipeline`` compares all three).

Every mode feeds :meth:`Trainer.train` through ``macro_batches(fuse)``,
yielding ``(device_batch, n_steps, fused, examples)`` — fused groups arrive
pre-stacked for the K-step ``multi_step`` — and records
:class:`~pdnlp_tpu.utils.metrics.TransportStats` so the transport win is
measured, not asserted.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from pdnlp_tpu.utils.metrics import TransportStats

Batch = Dict[str, np.ndarray]


def _nbytes(batch) -> int:
    return sum(getattr(v, "nbytes", 0) for v in batch.values())


def _seq_of(batch) -> int:
    """Token width of a (host or device) batch — the bucket key."""
    return int(batch["input_ids"].shape[-1])


def _tokens_real(host: Batch) -> int:
    """Non-[PAD] token positions in a HOST batch (numpy sum — never called
    on device arrays; the resident pipeline counts from host lengths)."""
    return int(host["attention_mask"].sum())


class _MacroStage:
    """Preallocated staging buffers for the K-stacked macro-batch.

    ``Trainer._macro_batches`` used to build every fused group with a fresh
    ``np.stack`` per key — K x batch bytes of allocation churn per group.
    This stages into buffers allocated once and reused, ping-ponging
    between TWO buffers so the group yielded previously survives one
    further iteration (the prefetch pipeline's lookahead depth).

    Reuse is only sound when the upload COPIES the host memory.  An
    identity ``put`` (single-device Trainer default) or a zero-copy
    ``device_put`` would alias the staging buffer into the in-flight batch
    and the next group would overwrite it mid-step — :meth:`verify` checks
    exactly that on the first uploaded group (``np.shares_memory`` against
    the uploaded arrays' host view) and permanently disables reuse when
    aliasing is detected, falling back to fresh per-group stacks.
    """

    def __init__(self, k: int):
        self.k = int(k)
        self.enabled = True
        self.verified = False
        # buffers keyed by the group's shape signature: bucket mode feeds
        # several static shapes through one stage (one ping-pong pair per
        # bucket — still a bounded, len(buckets)-sized set)
        self._bufs: dict = {}
        self._i: dict = {}

    @staticmethod
    def _sig(batch: Batch) -> tuple:
        return tuple(sorted((key, v.shape, str(v.dtype))
                            for key, v in batch.items()))

    def stack(self, group) -> Batch:
        """One ``[K, ...]`` host macro-batch from ``k`` host batches."""
        if not self.enabled or self.k <= 1:
            return {key: np.stack([b[key] for b in group])
                    for key in group[0]}
        sig = self._sig(group[0])
        if sig not in self._bufs:
            def alloc():
                return {key: np.empty((self.k,) + v.shape, v.dtype)
                        for key, v in group[0].items()}
            self._bufs[sig] = (alloc(), alloc())
            self._i[sig] = 0
            # the stage must not alias its sources (a loader yielding views
            # of cached arrays would be corrupted by the copy-in below)
            assert not any(
                np.shares_memory(self._bufs[sig][0][key], b[key])
                for b in group for key in group[0])
        buf = self._bufs[sig][self._i[sig]]
        self._i[sig] ^= 1
        for i, b in enumerate(group):
            for key in buf:
                np.copyto(buf[key][i], b[key])
        return buf

    def verify(self, host: Batch, uploaded) -> None:
        """First-upload aliasing check: disable reuse if ``uploaded`` still
        reads the staging memory (identity put / zero-copy device_put)."""
        if self.verified or not self.enabled or not self._bufs:
            return
        self.verified = True
        for key, v in host.items():
            up = uploaded.get(key) if hasattr(uploaded, "get") else None
            if up is None:
                continue
            view = up if isinstance(up, np.ndarray) else None
            if view is None:
                try:
                    view = np.asarray(up)  # CPU jax.Array: possibly a view
                except Exception:
                    continue  # no host view obtainable -> device copy: safe
            if np.shares_memory(v, view):
                self.enabled = False
                self._bufs = {}
                return


def host_macro_batches(loader, k: int, stage: Optional[_MacroStage] = None,
                       ) -> Iterator[Tuple[Batch, int, bool, int]]:
    """Yield ``(host_batch, n_steps, fused, examples)``: groups of ``k``
    loader batches stacked on a leading step axis, remainder as singles.

    A fused group assembled through ``stage`` is only valid until the next
    iteration (the buffers are reused) — consumers must upload before
    advancing, which every pipeline and the Trainer's classic path do.

    Fusion is SHAPE-homogeneous: a group only stacks batches of identical
    shape (the scanned multi-step is one compiled program per shape).
    Under bucket mode the length-grouped sampler orders batches in
    ``k``-runs per bucket, so groups straddle a bucket boundary only at
    bucket tails — those flush as single-step dispatches and the compile
    count stays ``len(buckets) x {single, fused}``.
    """
    if k <= 1:
        for b in loader:
            yield b, 1, False, int(b["example_weight"].sum())
        return
    stage = stage or _MacroStage(k)
    buf = []
    for b in loader:
        if buf and _seq_of(b) != _seq_of(buf[0]):
            # bucket boundary: never stack mixed shapes — dispatch the
            # partial run as singles rather than compile a K'-step variant
            for x in buf:
                yield x, 1, False, int(x["example_weight"].sum())
            buf = []
        buf.append(b)
        if len(buf) == k:
            ex = sum(int(x["example_weight"].sum()) for x in buf)
            yield stage.stack(buf), k, True, ex
            buf = []
    for b in buf:
        yield b, 1, False, int(b["example_weight"].sum())


class InputPipeline:
    """Base: wraps a host ``DataLoader`` + the strategy's ``put``.

    Quacks like the loader (``len``/``set_epoch``/``iter`` over HOST
    batches) so existing call sites keep working; the Trainer consumes
    :meth:`macro_batches`, which yields DEVICE batches.
    """

    mode = "sync"

    def __init__(self, loader, put: Optional[Callable] = None,
                 put_fused: Optional[Callable] = None,
                 stats: Optional[TransportStats] = None, tracer=None):
        self.loader = loader
        self.put = put or (lambda b: b)
        self.put_fused = put_fused or self.put
        self.stats = stats or TransportStats()
        self.stats.mode = self.mode
        # obs tracer for h2d_put spans; None resolves to the process-global
        # tracer LAZILY (the Trainer configures it from --trace after the
        # pipeline is built)
        self._tracer = tracer

    @property
    def tracer(self):
        if self._tracer is not None:
            return self._tracer
        from pdnlp_tpu.obs.trace import get_tracer

        return get_tracer()

    def __len__(self) -> int:
        return len(self.loader)

    def set_epoch(self, epoch: int) -> None:
        self.loader.set_epoch(epoch)

    def __iter__(self):
        return iter(self.loader)

    def macro_batches(self, fuse: int = 1
                      ) -> Iterator[Tuple[Batch, int, bool, int]]:
        raise NotImplementedError

    def warmup_batch(self, fuse: int = 1):
        """One device batch with the hot loop's exact shape/sharding/
        placement (for resident mode: a real gather output) — what
        ``warmup_compile``/``probe_steps_per_sec`` lower against.  The
        underlying generator is closed immediately; no epoch state leaks."""
        gen = self.macro_batches(fuse)
        try:
            for batch, _n, _fused, _ex in gen:
                return batch
            return None
        finally:
            gen.close()


class SyncPipeline(InputPipeline):
    """The reference behavior, instrumented: upload inline in the loop."""

    mode = "sync"

    def macro_batches(self, fuse: int = 1):
        stage = _MacroStage(fuse)
        tr = self.tracer
        for host, n, fused, ex in host_macro_batches(self.loader, fuse,
                                                     stage):
            put = self.put_fused if fused else self.put
            # deliberately times HOST seconds blocked in the upload (the
            # put-wait metric), not device compute — no barrier wanted
            t0 = time.perf_counter()
            with tr.span("h2d_put", bytes=_nbytes(host)):
                dev = put(host)
            # jaxlint: disable=R4 — put-wait is a host metric by design
            self.stats.record_upload(_nbytes(host), time.perf_counter() - t0)
            if fused:
                stage.verify(host, dev)
            self.stats.record_batch(
                n, int(host["example_weight"].size), ex,
                seq_len=_seq_of(host), tokens=int(host["input_ids"].size),
                tokens_real=_tokens_real(host))
            yield dev, n, fused, ex


class DevicePrefetchPipeline(InputPipeline):
    """Double-buffered upload: ``put`` batch *k+1* while step *k* executes.

    A background worker uploads ahead of the loop, bounded by a 1-slot
    semaphore: at most ONE batch is ever in flight (uploaded but not yet
    handed over), released only when the loop asks for the next batch — so
    the upload of *k+1* genuinely overlaps step *k*'s device execution
    instead of queueing a pile of device memory.  Worker exceptions
    (collation or ``put``) propagate to the consumer; abandoning the
    iterator mid-epoch stops the worker in one bounded join.
    """

    mode = "prefetch"

    _POLL = 0.1

    def macro_batches(self, fuse: int = 1):
        q: queue.Queue = queue.Queue()
        slots = threading.Semaphore(1)
        stop = threading.Event()
        done = object()

        def worker():
            try:
                tr = self.tracer
                stage = _MacroStage(fuse)
                for host, n, fused, ex in host_macro_batches(
                        self.loader, fuse, stage):
                    while not slots.acquire(timeout=self._POLL):
                        if stop.is_set():
                            return
                    if stop.is_set():
                        return
                    self.stats.put_started()
                    put = self.put_fused if fused else self.put
                    t0 = time.perf_counter()
                    # span recorded from THIS worker thread: the export
                    # shows the upload overlapping the step on its own tid
                    with tr.span("h2d_put", bytes=_nbytes(host)):
                        dev = put(host)
                    self.stats.record_upload(
                        _nbytes(host),
                        # jaxlint: disable=R4 — put-wait is a host metric
                        time.perf_counter() - t0)
                    if fused:
                        stage.verify(host, dev)
                    # batch telemetry measured from the HOST batch here in
                    # the worker (the consumer only ever sees device arrays)
                    meta = (int(host["example_weight"].size), _seq_of(host),
                            int(host["input_ids"].size), _tokens_real(host))
                    q.put((dev, n, fused, ex, meta))  # unbounded: no block
                q.put(done)
            except BaseException as e:  # propagate, don't vanish
                q.put(e)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is done:
                    break
                if isinstance(item, BaseException):
                    raise item
                dev, n, fused, ex, meta = item
                rows, seq, tokens, tokens_real = meta
                self.stats.put_delivered()
                self.stats.record_batch(n, rows, ex, seq_len=seq,
                                        tokens=tokens,
                                        tokens_real=tokens_real)
                slots.release()  # let the worker upload the NEXT batch now
                yield dev, n, fused, ex
        finally:
            stop.set()
            t.join(timeout=2.0)  # puts/acquires are stop-aware: one join


class DeviceResidentPipeline(InputPipeline):
    """Zero-transport epochs: the encoded split lives in HBM.

    The :class:`EncodedDataset` arrays are uploaded once (sharded over the
    mesh's data axis when their row count divides it, replicated
    otherwise); each epoch uploads only the seeded permutation indices
    (``[steps, rows]`` int32, ~40 KB for this corpus) plus one zero
    counter.  Per step, a jitted gather indexes the permutation with an
    ON-DEVICE counter and masks filler rows — bitwise identical batches to
    ``EncodedDataset.take`` with zero steady-state host->device bytes.

    Resume fast-forward dispatches (cheap, transport-free) gathers for the
    skipped steps; the counter/order is untouched so the remaining stream
    is bitwise the host loader's.
    """

    mode = "resident"

    def __init__(self, loader, put: Optional[Callable] = None,
                 put_fused: Optional[Callable] = None, mesh=None,
                 stats: Optional[TransportStats] = None, tracer=None):
        super().__init__(loader, put, put_fused, stats, tracer)
        if loader.encoded is None:
            raise ValueError(
                "device-resident pipeline needs the loader's EncodedDataset "
                "— a collator-driven (shuffling/augmenting) loader has no "
                "frozen encoding to upload; use pipeline='prefetch'")
        import jax

        self.mesh = mesh
        self.rows = loader.batch_size
        # gathers keyed (k, seq_len): bucket mode compiles one per
        # (step-variant, bucket) — bounded, like the step programs.  The
        # RESIDENCY stays one full-width copy; a bucket batch is the same
        # gather plus a free on-device column slice, so per-bucket service
        # costs no extra HBM.
        self._gathers: Dict[tuple, Callable] = {}
        enc = loader.encoded
        self._seq = getattr(enc, "seq_len", None)
        self._lengths = enc.lengths() if hasattr(enc, "lengths") else None
        # per-row real-example counts (packed rows carry several; plain
        # encodings one) — host-side, for the transport telemetry only
        self._row_examples = (
            (enc.arrays["example_weight"] > 0).sum(1).astype(np.int64)
            if "example_weight" in enc.arrays else None)
        # label SLOTS per row (M for packed [N, M] channels, 1 otherwise):
        # the row-level waste ratio counts slots, matching what sync /
        # prefetch derive from the host batch's example_weight.size — the
        # physical row count alone would make rows_real exceed rows under
        # packing and push the ratio negative
        self._slots_per_row = (
            int(enc.arrays["example_weight"].shape[1])
            if self._row_examples is not None else 1)
        nbytes = sum(v.nbytes for v in enc.arrays.values())
        t0 = time.perf_counter()
        # the one-time residency upload: an amortized h2d_put span (the
        # trace shows the ~14 MB upload once, then silence every step)
        with self.tracer.span("h2d_put", bytes=nbytes, in_loop=False,
                              what="resident_dataset"):
            self.arrays = {k: self._place(v) for k, v in enc.arrays.items()}
            jax.block_until_ready(list(self.arrays.values()))
        self.stats.record_upload(nbytes, time.perf_counter() - t0,
                                 in_loop=False)

    # ------------------------------------------------------------ placement
    def _place(self, v: np.ndarray):
        import jax

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from pdnlp_tpu.parallel.mesh import DATA_AXIS

            size = self.mesh.shape.get(DATA_AXIS, 1)
            spec = P(DATA_AXIS) if v.shape[0] % size == 0 else P()
            return jax.device_put(v, NamedSharding(self.mesh, spec))
        import jax.numpy as jnp

        return jnp.asarray(v)

    def _replicate(self, v: np.ndarray):
        import jax

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.device_put(v, NamedSharding(self.mesh, P()))
        import jax.numpy as jnp

        return jnp.asarray(v)

    # ---------------------------------------------------------- the gather
    def _gather(self, k: int, seq_len: int = 0) -> Callable:
        """Jitted ``(arrays, perm, nreal, counter) -> (batch, counter+1)``.

        ``perm``: ``[G, k, rows]`` int32 epoch permutation; ``nreal``:
        ``[G, k]`` real-row counts.  The counter is a DEVICE scalar — after
        the per-epoch index upload, dispatching this costs zero
        host->device bytes.  Filler rows (index padding) are masked to the
        exact zeros ``EncodedDataset.take`` pads with, so the output is
        bitwise the host loader's batch.

        ``seq_len`` (bucket mode) column-slices the full-width token
        channels to the bucket on device — same bytes ``take(...,
        seq_len=...)`` produces on host, zero extra residency.  A dataset
        carrying its own ``example_weight`` channel (packed rows) keeps it:
        the row mask zeroes filler rows' weights exactly like the host
        path.
        """
        key = (k, int(seq_len))
        if key in self._gathers:
            return self._gathers[key]
        import jax
        import jax.numpy as jnp

        rows = self.rows
        full = self._seq

        def assemble(arrays, perm, nreal, counter):
            idx = jax.lax.dynamic_index_in_dim(perm, counter, 0,
                                               keepdims=False)   # [k, rows]
            nr = jax.lax.dynamic_index_in_dim(nreal, counter, 0,
                                              keepdims=False)    # [k]
            mask = jnp.arange(rows, dtype=jnp.int32)[None, :] < nr[:, None]
            batch = {}
            for akey, v in arrays.items():
                g = jnp.take(v, idx.reshape(-1), axis=0)
                if seq_len and v.ndim == 2 and full and v.shape[1] == full \
                        and seq_len < full:
                    g = g[:, :seq_len]
                g = g.reshape((k, rows) + g.shape[1:])
                m = mask.reshape(mask.shape + (1,) * (g.ndim - mask.ndim))
                g = g * m.astype(g.dtype)
                batch[akey] = g[0] if k == 1 else g
            if "example_weight" not in arrays:
                ew = mask.astype(jnp.float32)
                batch["example_weight"] = ew[0] if k == 1 else ew
            return batch, counter + 1

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from pdnlp_tpu.parallel.mesh import DATA_AXIS

            rep = NamedSharding(self.mesh, P())
            row_spec = (P(DATA_AXIS) if k == 1 else P(None, DATA_AXIS)) \
                if self.rows % self.mesh.shape.get(DATA_AXIS, 1) == 0 else P()
            batch_sh = NamedSharding(self.mesh, row_spec)
            out_sh = ({out_key: batch_sh for out_key in
                       set(self.arrays) | {"example_weight"}}, rep)
            fn = jax.jit(assemble, out_shardings=out_sh)
        else:
            fn = jax.jit(assemble)
        self._gathers[key] = fn
        return fn

    # ------------------------------------------------------------ the epoch
    def macro_batches(self, fuse: int = 1):
        k = max(1, int(fuse))
        chunks = list(self.loader._chunks())  # the sampler's exact chunking
        if not chunks:
            return
        # consecutive same-bucket runs: under the length-grouped sampler a
        # run is one bucket's stretch of batches; the classic samplers
        # yield exactly one run (seq 0 = the dataset's full width), which
        # reproduces the old fused+tail segmentation bit for bit
        runs: list = []
        for c, seq in chunks:
            if not runs or runs[-1][0] != seq:
                runs.append((seq, []))
            runs[-1][1].append(c)

        # build every segment's gather object first (jit construction is
        # cheap; compilation happens at first dispatch, not in the timed
        # upload window), then time the index uploads as ONE amortized
        # record — whatever the run structure, resident mode's epoch
        # transport stays a single ~40 KB permutation upload
        t0 = time.perf_counter()
        tr0 = self.tracer.now()
        segments = []
        total_bytes = 4  # the zero counter(s)
        for seq, cs in runs:
            steps = len(cs)
            n_fused, n_tail = (steps // k, steps % k) if k > 1 else (0, steps)
            counts = np.asarray([len(c) for c in cs], np.int32)
            padded = np.zeros((steps, self.rows), np.int32)
            for i, c in enumerate(cs):
                padded[i, : len(c)] = c
            total_bytes += padded.nbytes + counts.nbytes
            if n_fused:
                segments.append((self._gather(k, seq), k, n_fused, seq,
                                 self._replicate(
                                     padded[: n_fused * k].reshape(
                                         n_fused, k, self.rows)),
                                 self._replicate(
                                     counts[: n_fused * k].reshape(
                                         n_fused, k)),
                                 cs[: n_fused * k]))
            if n_tail:
                segments.append((self._gather(1, seq), 1, n_tail, seq,
                                 self._replicate(
                                     padded[n_fused * k:].reshape(
                                         n_tail, 1, self.rows)),
                                 self._replicate(
                                     counts[n_fused * k:].reshape(
                                         n_tail, 1)),
                                 cs[n_fused * k:]))
        # the per-epoch permutation-index upload (~40 KB): the ONLY
        # steady-state transport resident mode pays — one amortized
        # h2d_put span per epoch in the trace
        self.tracer.record("h2d_put", tr0, self.tracer.now(),
                           bytes=total_bytes,
                           in_loop=False, what="epoch_indices")
        self.stats.record_upload(
            total_bytes,
            # jaxlint: disable=R4 — host wait of the index upload, by design
            time.perf_counter() - t0, in_loop=False)

        for gather, seg_k, groups, seq, perm, nreal, seg_chunks in segments:
            seq_eff = int(seq) if seq else int(self._seq or 0)
            # telemetry precomputed per segment (one host pass per epoch,
            # len(seg_chunks) == groups * seg_k by construction): the
            # dispatch loop below stays O(1) host work per group
            ex_g = np.asarray(
                [self._row_examples[c].sum()
                 if self._row_examples is not None else len(c)
                 for c in seg_chunks], np.int64).reshape(groups, seg_k).sum(1)
            tok_g = np.asarray(
                [self._lengths[c].sum() if self._lengths is not None else 0
                 for c in seg_chunks], np.int64).reshape(groups, seg_k).sum(1)
            counter = self._replicate(np.int32(0))
            for g in range(groups):
                batch, counter = gather(self.arrays, perm, nreal, counter)
                ex = int(ex_g[g])
                self.stats.record_batch(
                    seg_k, seg_k * self.rows * self._slots_per_row, ex,
                    seq_len=seq_eff,
                    tokens=seg_k * self.rows * seq_eff,
                    tokens_real=int(tok_g[g]))
                yield batch, seg_k, seg_k > 1, ex


def build_pipeline(args, loader, put: Optional[Callable] = None,
                   put_fused: Optional[Callable] = None, mesh=None,
                   allow_resident: bool = True,
                   stats: Optional[TransportStats] = None,
                   tracer=None) -> InputPipeline:
    """The mode decision, in one place.

    ``args.pipeline``: ``auto`` (default) picks ``resident`` when eligible,
    else ``prefetch``; naming a mode forces it — and forcing ``resident``
    when it must be refused raises with the reason instead of silently
    degrading.  Eligibility for ``resident``: the loader carries an
    ``EncodedDataset`` (deterministic frozen encoding — a shuffling or
    augmenting collator is refused), the encoded split fits the
    ``--pipeline_hbm_mb`` budget, the run is single-process, and the
    caller's batch placement is the plain data-axis upload
    (``allow_resident`` — sp/pp slice batches differently).
    """
    import jax

    mode = getattr(args, "pipeline", "auto") or "auto"
    if mode not in ("auto", "resident", "prefetch", "sync"):
        raise ValueError(f"unknown pipeline mode {mode!r}; use "
                         "auto|resident|prefetch|sync")
    refusal = None
    if not allow_resident:
        refusal = ("this strategy slices batches across seq/stage axes — "
                   "the resident gather assumes plain data-axis placement")
    elif getattr(loader, "encoded", None) is None \
            or not hasattr(loader.encoded, "arrays"):
        # no EncodedDataset, or an encoded-like without ONE rectangular
        # array set (MultiWidthPackedDataset holds per-width groups) —
        # nothing the resident gather could hold as a single residency
        refusal = ("loader has no resident-eligible EncodedDataset "
                   "(collator-driven batches may shuffle/augment per "
                   "epoch; multi-width packed splits have no single "
                   "rectangular encoding to hold resident)")
    elif jax.process_count() > 1:
        refusal = "multi-process run: the split spans host processes"
    else:
        budget = int(getattr(args, "pipeline_hbm_mb", 128)) * (1 << 20)
        nbytes = sum(v.nbytes for v in loader.encoded.arrays.values())
        if nbytes > budget:
            refusal = (f"encoded split is {nbytes / 2**20:.1f} MB, over the "
                       f"--pipeline_hbm_mb {budget // 2**20} MB budget")
    if mode == "resident" and refusal is not None:
        raise ValueError(f"pipeline='resident' refused: {refusal}")
    if mode == "auto":
        mode = "resident" if refusal is None else "prefetch"
    cls = {"resident": DeviceResidentPipeline,
           "prefetch": DevicePrefetchPipeline,
           "sync": SyncPipeline}[mode]
    if cls is DeviceResidentPipeline:
        return cls(loader, put, put_fused, mesh=mesh, stats=stats,
                   tracer=tracer)
    return cls(loader, put, put_fused, stats=stats, tracer=tracer)
