"""Model configuration registry.

The reference builds its model as HF ``BertConfig`` +
``BertForSequenceClassification.from_pretrained`` with ``num_labels=6``
(``/root/reference/single-gpu-cls.py:252-255``).  Here the architecture is a
first-class config: one frozen dataclass, a named registry (``bert-base``
matches ``chinese-bert-wwm-ext``'s shape: 12L/768H/12 heads, vocab 21128),
plus small variants used by tests and the multichip dryrun.
"""
from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 21_128
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    attn_dropout: float = 0.1
    num_labels: int = 6
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    gelu: str = "erf"             # "erf" = exact (HF BertConfig
                                  # hidden_act="gelu", the reference model);
                                  # "tanh" = polynomial approximation —
                                  # measured +7% fused-step rate at batch 64
                                  # on v5e and +0.7pt fine-tune accuracy
                                  # when pretrained with it end to end
                                  # (results/profile_r05.json gelu_tanh*,
                                  # bench recipe note)
    # --- mixture-of-experts (0 experts = dense MLP; no reference twin) ---
    moe_experts: int = 0          # experts per layer's MLP
    moe_top_k: int = 2            # experts combined per token
    moe_aux_coef: float = 0.01    # Switch-style load-balancing loss weight
    moe_dispatch: str = "grouped" # "grouped": capacity-based gather +
                                  # per-expert matmuls, O(k*capacity) FFN
                                  # cost; "dense": every expert computes
                                  # every token, O(E) — exact, no drops,
                                  # the small-E fallback and parity oracle
    moe_capacity_factor: float = 1.25  # slots per expert =
                                  # ceil(cf * k * tokens / E); tokens over
                                  # capacity fall back to the residual path

    @property
    def head_dim(self) -> int:
        assert self.hidden_size % self.num_heads == 0
        return self.hidden_size // self.num_heads

    def replace(self, **kw) -> "BertConfig":
        return dataclasses.replace(self, **kw)


_REGISTRY = {
    # chinese-bert-wwm-ext shape (BERT-base, ~102M params at vocab 21128)
    "bert-base": BertConfig(),
    # scaled-down variants for CI / virtual-mesh dryruns
    "bert-small": BertConfig(hidden_size=512, num_layers=4, num_heads=8,
                             intermediate_size=2048),
    "bert-tiny": BertConfig(hidden_size=128, num_layers=2, num_heads=2,
                            intermediate_size=512, max_position=128),
    # MoE variants: the dense MLP becomes moe_experts gated experts (the
    # expert-parallel "ep" sharding mode splits them over an "expert" axis)
    "bert-base-moe": BertConfig(moe_experts=4),
    "bert-tiny-moe": BertConfig(hidden_size=128, num_layers=2, num_heads=2,
                                intermediate_size=512, max_position=128,
                                moe_experts=4),
    # long-context variants: a 4x position table for the sequence-parallel
    # (ring attention) path, whose whole point is sequences no single
    # device wants to hold — each seq shard stores/attends seq/N locally
    # and the position table covers the GLOBAL length
    "bert-base-long": BertConfig(max_position=2048),
    "bert-tiny-long": BertConfig(hidden_size=128, num_layers=2, num_heads=2,
                                 intermediate_size=512, max_position=512),
}


def get_config(name: str, vocab_size: Optional[int] = None,
               num_labels: Optional[int] = None, **overrides) -> BertConfig:
    """Look up a registered architecture, overriding data-dependent fields
    (vocab size comes from the corpus-built vocab at runtime)."""
    try:
        cfg = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; use one of {available_models()}") from None
    kw = dict(overrides)
    if vocab_size is not None:
        kw["vocab_size"] = vocab_size
    if num_labels is not None:
        kw["num_labels"] = num_labels
    return cfg.replace(**kw) if kw else cfg


def available_models():
    return sorted(_REGISTRY)


def args_overrides(args) -> dict:
    """Config overrides an ``Args`` carries when explicitly set (None =
    keep the registry default) — shared by every ``get_config(args.model)``
    call site so CLI knobs can't silently apply on one path only."""
    kw = {}
    for f in ("moe_dispatch", "moe_capacity_factor", "moe_top_k",
              "moe_experts", "gelu"):
        v = getattr(args, f, None)
        if v is not None:
            kw[f] = v
    return kw
