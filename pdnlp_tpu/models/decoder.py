"""Causal decoder head over the BERT trunk + the KV-cache decode math.

The serving tier was classification-shaped: one forward, one logit row per
request.  Generative decoding inverts the cost structure — autoregressive
decode is memory-bandwidth-bound, so tokens/s is won on *not recomputing*
the prompt every token.  This module is the pure-math half of that story
(the serving half — slots, continuous batching, budgets — lives in
``pdnlp_tpu.serve.decode``):

- **one trunk, three programs**: the decoder reuses the classifier's param
  tree (``bert.init_params`` — embeddings, stacked layers) under an LM
  head shaped exactly like the MLM head (``init_lm_head`` — transform +
  LayerNorm + decoder TIED to the word embeddings), so any strategy
  checkpoint serves generatively without conversion.  :func:`prefill`
  runs the prompt causally and RETURNS the per-layer K/V it computed;
  :func:`decode_step` advances one token against a slot-indexed cache;
  :func:`infill_logits` is the bidirectional MLM-infilling scorer (same
  trunk, no causal mask — BERT's native objective served online).
- **KV cache layout** ``[L, slots, max_len, N, D]``: layer-major so the
  layer scan streams one ``[slots, max_len, N, D]`` slab per step;
  ``max_len`` ahead of heads so cached keys keep the trunk's ``[B, S, N,
  D]`` attention layout — cached and recomputed attention then share ONE
  einsum/reduction shape, which is what makes the bitwise decode-parity
  contract below provable instead of approximate.
- **the bitwise contract**: incremental decode over a live cache is
  bitwise equal, per step, to a FULL RECOMPUTE from a cold cache — a
  fresh prefill of the prompt plus a from-scratch replay of every
  generated token, nothing reused (``tests/test_decode.py`` pins it; the
  bench gates it mid-storm).  The contract is provable because every
  decode shape is FIXED (``[rows, 1]`` tokens, ``[rows]`` positions, the
  preallocated cache), so both sides run identical programs on
  bitwise-equal inputs, and the -1e9 additive masks zero invisible keys'
  probabilities EXACTLY (masked cache rows contribute exact ``+0.0``
  regardless of their stale contents).  Against the one-shot WIDE causal
  forward the comparison is argmax-exact within ~1e-6 instead: XLA's CPU
  gemm blocks the contraction differently per row extent (measured:
  ``[3, 512] @ [512, 128]`` vs the same rows at extent 96 differ by
  ULPs), so a ``[rows, 1]`` pass and a ``[rows, S]`` pass are only
  accumulation-order-equal, not bit-equal, on that backend.
- **int8 KV** (:func:`quantize_kv` / :func:`dequantize_kv`): the cache
  stores int8 against per-(layer, head, channel) symmetric scale tables —
  the PR-6 per-channel machinery pointed at activations.  Scales are
  CALIBRATED (:func:`calibrate_kv_scales` — a seeded synthetic forward,
  identical math offline in ``scripts/quantize_ckpt.py --kv_calib`` and
  online at engine warmup, so the two routes can never disagree); new K/V
  quantize on write, the whole cache dequantizes by one broadcast
  multiply on read, and no fp32 copy of the cache ever persists.

The hot decode shapes are fixed by construction — ``[rows, 1]`` tokens,
``[rows]`` positions, the preallocated cache — so a jitted
:func:`decode_step` can never retrace after its first trace (the serve
engine donates the cache buffers across steps; jaxlint R16 polices the
rebuild-the-cache anti-pattern).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pdnlp_tpu.models import bert
from pdnlp_tpu.models.config import BertConfig
from pdnlp_tpu.ops.attention import dot_product_attention, mask_bias

Params = Dict[str, Any]

#: seeded synthetic calibration batch (shared by the offline artifact and
#: engine self-calibration — identical inputs => identical scale tables)
CALIB_SEED = 20240801
CALIB_ROWS = 4


def init_lm_head(key: jax.Array, cfg: BertConfig) -> Params:
    """LM head params — the MLM head's exact tree (transform + LayerNorm +
    per-token bias; decoder tied to the word embeddings), kept as a
    SEPARATE tree so classifier checkpoints load into the trunk unchanged.
    One init for both roles: MLM infilling and causal next-token share the
    head, which is what lets a single checkpoint serve both scorers."""
    return bert.init_mlm_head(key, cfg)


def lm_logits(params: Params, head: Params, cfg: BertConfig,
              hidden: jax.Array, *, dtype=jnp.float32) -> jax.Array:
    """[B, S, H] -> [B, S, vocab] fp32 (tied decoder — ``bert.mlm_logits``)."""
    return bert.mlm_logits(params, head, cfg, hidden, dtype=dtype)


# --------------------------------------------------------------- attention

def _qkv(x: jax.Array, lp: Params, cfg: BertConfig, dtype):
    B, S = x.shape[0], x.shape[1]
    N, D = cfg.num_heads, cfg.head_dim

    def heads(t):
        return t.reshape(B, S, N, D)

    return (heads(bert._dense(x, lp["q"], dtype)),
            heads(bert._dense(x, lp["k"], dtype)),
            heads(bert._dense(x, lp["v"], dtype)))


def _finish_layer(x, lp, cfg, attn, dtype):
    """Post-attention half of one trunk layer (deterministic serve form):
    output projection + residual LN + MLP + residual LN — ``bert``'s exact
    ops, so decoder hidden states match the trunk bit for bit."""
    B, S = x.shape[0], x.shape[1]
    attn = bert._dense(attn.reshape(B, S, -1), lp["o"], dtype)
    x = bert._layer_norm(x + attn, lp["attn_ln"]["scale"],
                         lp["attn_ln"]["bias"], cfg.layer_norm_eps)
    h = bert._gelu(bert._dense(x, lp["up"], dtype), cfg.gelu)
    h = bert._dense(h, lp["down"], dtype)
    return bert._layer_norm(x + h, lp["mlp_ln"]["scale"],
                            lp["mlp_ln"]["bias"], cfg.layer_norm_eps)


def _check_dense_trunk(layers: Params) -> None:
    if "gate" in layers:
        raise ValueError(
            "generative decoding over an MoE trunk is not supported — the "
            "expert dispatch has no cached single-token form yet; serve a "
            "dense checkpoint (--model without -moe)")


# ----------------------------------------------------------------- prefill

def run_layers_kv(layers: Params, cfg: BertConfig, x: jax.Array, *,
                  bias: jax.Array, causal: bool = True,
                  dtype=jnp.float32, unroll=True
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Causal layer scan that also RETURNS what it computed: hidden
    [B, S, H] plus per-layer K/V stacked ``[L, B, S, N, D]`` — the arrays
    the serve engine scatters into its slot cache, at zero extra compute
    (prefill had to build them anyway; the classifier path just threw
    them away).  Attention rides ``ops.attention`` (the causal
    composition and its routing live there, not here)."""
    _check_dense_trunk(layers)

    def layer(carry, scanned):
        x = carry
        lp, _ = scanned
        q, k, v = _qkv(x, lp, cfg, dtype)
        # "auto" routes causal/decode shapes to XLA everywhere today
        # (routed_impl: the flash kernel has no causal term) while leaving
        # the decision at the ops routing point, not pinned here
        attn = dot_product_attention(q, k, v, bias, impl="auto",
                                     causal=causal)
        return _finish_layer(x, lp, cfg, attn, dtype), (k, v)

    li = jnp.arange(cfg.num_layers)
    x, (ks, vs) = jax.lax.scan(layer, x, (layers, li), unroll=unroll)
    return x, ks, vs


def prefill(params: Params, head: Params, cfg: BertConfig,
            input_ids: jax.Array,       # [B, S] int32 (left-aligned)
            attention_mask: jax.Array,  # [B, S] {0,1}
            last_pos: jax.Array,        # [B] int32: index of last real token
            *, dtype=jnp.float32, unroll=True
            ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Causal prompt forward: next-token logits [B, vocab] (fp32, read at
    each row's ``last_pos``) + the per-layer K/V ``[L, B, S, N, D]``.

    The mask is causal ∘ key-padding (``ops.attention.causal_bias`` — the
    sanctioned quadratic site, composed inside ``dot_product_attention``):
    with left-aligned prompts the causal term already hides padding from
    every real row, and the explicit padding term keeps the composition
    correct for any caller that right-pads."""
    zeros = jnp.zeros_like(input_ids)
    x, _ = bert.embed(params, cfg, input_ids, zeros, dtype=dtype,
                      deterministic=True)
    bias = mask_bias(attention_mask, jnp.float32)
    hidden, ks, vs = run_layers_kv(params["layers"], cfg, x, bias=bias,
                                   causal=True, dtype=dtype, unroll=unroll)
    h_last = jnp.take_along_axis(
        hidden, last_pos.astype(jnp.int32)[:, None, None], axis=1)  # [B,1,H]
    logits = lm_logits(params, head, cfg, h_last, dtype=dtype)[:, 0]
    return logits, ks, vs


# ------------------------------------------------------------------ decode

def quantize_kv(x: jax.Array, scale: jax.Array) -> jax.Array:
    """fp K/V rows -> int8 against per-(head, channel) scales (broadcast
    over leading dims) — the PR-6 symmetric per-channel rule on
    activations."""
    q = jnp.round(x.astype(jnp.float32) / scale)
    return jnp.clip(q, -127, 127).astype(jnp.int8)


def dequantize_kv(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """int8 cache slab -> compute dtype by one broadcast multiply (no fp32
    copy persists — the multiply fuses into the attention reads)."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def decode_step(params: Params, head: Params, cfg: BertConfig,
                tokens: jax.Array,   # [B, 1] int32: the CURRENT token
                cache_k: jax.Array,  # [L, B, max_len, N, D] (fp or int8)
                cache_v: jax.Array,
                pos: jax.Array,      # [B] int32: write position of `tokens`
                *, kv_scales: Optional[Tuple[jax.Array, jax.Array]] = None,
                dtype=jnp.float32, unroll=True
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One fixed-shape decode step: embed ``tokens`` at ``pos``, write
    their K/V into the cache at ``pos`` (``.at[].set`` — an in-place
    dynamic update on a donated buffer, never a rebuild), attend over
    positions ``<= pos``, return (next-token logits [B, vocab] fp32,
    cache_k', cache_v').

    Every shape here is static — [B, 1] tokens, [B] positions, the
    preallocated cache — so the jitted form holds exactly ONE compiled
    program (retrace-free by the same construction as ``infer_packed``).
    ``kv_scales`` = (k_scale, v_scale) ``[L, N, D]`` switches the cache to
    int8: new rows quantize before the write, slabs dequantize per layer
    at read.  The CURRENT token's K/V round-trips through the cache too —
    the step attends to what FUTURE steps will see, so int8 error is
    consistent across the stream instead of hidden on the diagonal."""
    _check_dense_trunk(params["layers"])
    B = tokens.shape[0]
    max_len = cache_k.shape[2]
    pos = pos.astype(jnp.int32)
    x, _ = bert.embed(params, cfg, tokens, jnp.zeros_like(tokens),
                      dtype=dtype, deterministic=True,
                      position_ids=pos[:, None])
    # linear visibility mask: key j visible iff j <= pos (prompt + already
    # decoded + the token just written); never a [S, S] term
    visible = (jnp.arange(max_len)[None, :] <= pos[:, None])
    bias = mask_bias(visible.astype(jnp.float32), jnp.float32)
    rows = jnp.arange(B)

    def layer(carry, scanned):
        x = carry
        if kv_scales is None:
            lp, _, ck, cv = scanned
        else:
            lp, _, ck, cv, ks_l, vs_l = scanned
        q, k_new, v_new = _qkv(x, lp, cfg, dtype)         # [B, 1, N, D]
        if kv_scales is None:
            ck = ck.at[rows, pos].set(k_new[:, 0])
            cv = cv.at[rows, pos].set(v_new[:, 0])
            kf, vf = ck, cv
        else:
            ck = ck.at[rows, pos].set(quantize_kv(k_new[:, 0], ks_l))
            cv = cv.at[rows, pos].set(quantize_kv(v_new[:, 0], vs_l))
            kf = dequantize_kv(ck, ks_l, dtype)
            vf = dequantize_kv(cv, vs_l, dtype)
        attn = dot_product_attention(q, kf, vf, bias, impl="auto")
        return _finish_layer(x, lp, cfg, attn, dtype), (ck, cv)

    li = jnp.arange(cfg.num_layers)
    xs = (params["layers"], li, cache_k, cache_v)
    if kv_scales is not None:
        xs = xs + (kv_scales[0], kv_scales[1])
    x, (cache_k, cache_v) = jax.lax.scan(layer, x, xs, unroll=unroll)
    logits = lm_logits(params, head, cfg, x, dtype=dtype)[:, 0]
    return logits, cache_k, cache_v


# ------------------------------------------------------------- paged cache
#
# The paged layout stores K/V as fixed-size pages ``[L, n_pages, page_sz,
# N, D]`` and a per-stream PAGE TABLE maps logical page -> physical page.
# Every program below works on the FLAT view ``[L, n_pages * page_sz, N,
# D]`` with host-computed (or in-program) flat indices ``physical_page *
# page_sz + offset``; dead rows and filler carry the OOB sentinel index
# ``n_pages * page_sz``, which ``mode="drop"`` scatters ignore and
# ``mode="fill"`` gathers read as 0.0 — a masked position's exact-zero
# contribution either way, so the slot-cache bitwise decode contract
# carries over unchanged (the gather reconstructs the same ``[B, max_len,
# N, D]`` extent the slot step attends over, with identical values at
# every visible position).


def paged_insert(pages_k: jax.Array,   # [L, P, page_sz, N, D]
                 pages_v: jax.Array,
                 ks: jax.Array,        # [L, B, S, N, D] (prefill output)
                 vs: jax.Array,
                 flat_pos: jax.Array,  # [B, S] int32 flat indices (OOB drop)
                 *, kv_scales: Optional[Tuple[jax.Array, jax.Array]] = None
                 ) -> Tuple[jax.Array, jax.Array]:
    """Scatter a prefill's K/V into pages: the paged analogue of the slot
    engine's cache insert.  ``flat_pos[b, s]`` is the flat page index for
    prompt b's position s (padding and filler rows carry the OOB
    sentinel, so they can never touch a live page)."""
    L, P, ps = pages_k.shape[0], pages_k.shape[1], pages_k.shape[2]
    tail = pages_k.shape[3:]
    if kv_scales is not None:
        ks = quantize_kv(ks, kv_scales[0][:, None, None])
        vs = quantize_kv(vs, kv_scales[1][:, None, None])
    pk = pages_k.reshape(L, P * ps, *tail)
    pv = pages_v.reshape(L, P * ps, *tail)
    pk = pk.at[:, flat_pos].set(ks.astype(pk.dtype), mode="drop")
    pv = pv.at[:, flat_pos].set(vs.astype(pv.dtype), mode="drop")
    return pk.reshape(pages_k.shape), pv.reshape(pages_v.shape)


def copy_pages(pages_k: jax.Array, pages_v: jax.Array,
               src: jax.Array,      # [n] physical page ids (OOB = no-op)
               dst: jax.Array       # [n]
               ) -> Tuple[jax.Array, jax.Array]:
    """Copy-on-write page duplication: ``pages[dst[i]] = pages[src[i]]``
    across all layers.  Unused rows carry the OOB sentinel ``P`` on both
    sides (``mode="fill"`` reads zeros, ``mode="drop"`` discards the
    write), so ONE fixed row count serves every claim round."""
    sk = jnp.take(pages_k, src, axis=1, mode="fill", fill_value=0)
    sv = jnp.take(pages_v, src, axis=1, mode="fill", fill_value=0)
    pages_k = pages_k.at[:, dst].set(sk, mode="drop")
    pages_v = pages_v.at[:, dst].set(sv, mode="drop")
    return pages_k, pages_v


def gather_pages(pages_k: jax.Array, pages_v: jax.Array,
                 src: jax.Array       # [rows] physical page ids (OOB = 0s)
                 ) -> Tuple[jax.Array, jax.Array]:
    """Export one stream's pages into a dense ``[L, rows, page_sz, N, D]``
    payload for a KV handoff.  ``src`` is ALWAYS the fixed
    ``pages_per_stream`` extent, padded with the OOB sentinel ``P``
    (``mode="fill"`` reads zeros there), so one compiled program serves
    every stream regardless of how many pages it actually holds — the
    real page count rides the page ids, never the shape."""
    out_k = jnp.take(pages_k, src, axis=1, mode="fill", fill_value=0)
    out_v = jnp.take(pages_v, src, axis=1, mode="fill", fill_value=0)
    return out_k, out_v


def scatter_pages(pages_k: jax.Array, pages_v: jax.Array,
                  payload_k: jax.Array,  # [L, rows, page_sz, N, D]
                  payload_v: jax.Array,
                  dst: jax.Array         # [rows] physical page ids (OOB drop)
                  ) -> Tuple[jax.Array, jax.Array]:
    """Import a handoff payload into freshly-allocated pages: the receive
    half of :func:`gather_pages`.  ``dst`` rows past the stream's real
    page count carry the OOB sentinel ``P`` and their (zero-filled)
    payload rows are dropped, so the import is the same ONE fixed-shape
    program for every stream."""
    pages_k = pages_k.at[:, dst].set(payload_k.astype(pages_k.dtype),
                                     mode="drop")
    pages_v = pages_v.at[:, dst].set(payload_v.astype(pages_v.dtype),
                                     mode="drop")
    return pages_k, pages_v


def _flat_gather_idx(page_table: jax.Array, page_sz: int) -> jax.Array:
    """[B, MP] page table -> [B, MP * page_sz] flat gather indices.
    Sentinel table entries (>= P) map past the flat extent and read 0."""
    B, MP = page_table.shape
    offs = jnp.arange(page_sz, dtype=jnp.int32)
    return (page_table[:, :, None] * page_sz
            + offs[None, None, :]).reshape(B, MP * page_sz)


def paged_decode_step(params: Params, head: Params, cfg: BertConfig,
                      tokens: jax.Array,      # [B, 1] int32
                      pages_k: jax.Array,     # [L, P, page_sz, N, D]
                      pages_v: jax.Array,
                      page_table: jax.Array,  # [B, MP] int32 (sentinel P)
                      pos: jax.Array,         # [B] int32 write positions
                      *, kv_scales: Optional[Tuple[jax.Array,
                                                   jax.Array]] = None,
                      dtype=jnp.float32, unroll=True
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """:func:`decode_step` over a paged cache: write the current token's
    K/V at ``page_table[b, pos // page_sz] * page_sz + pos % page_sz``,
    gather each row's logical ``[max_len]`` view through its table, and
    attend with the SAME linear visibility mask and extent as the slot
    step — bitwise-equal logits on bitwise-equal cache contents (module
    note above).  Shapes are all static ([B, 1] tokens, [B, MP] table,
    preallocated pages), so the jitted form holds ONE compiled program."""
    _check_dense_trunk(params["layers"])
    L, P, ps = pages_k.shape[0], pages_k.shape[1], pages_k.shape[2]
    tail = pages_k.shape[3:]
    B, MP = page_table.shape
    max_len = MP * ps
    pos = pos.astype(jnp.int32)
    x, _ = bert.embed(params, cfg, tokens, jnp.zeros_like(tokens),
                      dtype=dtype, deterministic=True,
                      position_ids=pos[:, None])
    visible = (jnp.arange(max_len)[None, :] <= pos[:, None])
    bias = mask_bias(visible.astype(jnp.float32), jnp.float32)
    gidx = _flat_gather_idx(page_table, ps)                    # [B, max_len]
    lp = pos // ps
    phys = jnp.take_along_axis(page_table, lp[:, None], axis=1)[:, 0]
    # dead rows ride with sentinel tables: their write lands OOB (dropped)
    wflat = jnp.where(phys < P, phys * ps + pos % ps, P * ps)  # [B]
    pk = pages_k.reshape(L, P * ps, *tail)
    pv = pages_v.reshape(L, P * ps, *tail)

    def layer(carry, scanned):
        x = carry
        if kv_scales is None:
            lp_, _, pk_l, pv_l = scanned
        else:
            lp_, _, pk_l, pv_l, ks_l, vs_l = scanned
        q, k_new, v_new = _qkv(x, lp_, cfg, dtype)             # [B, 1, N, D]
        if kv_scales is None:
            pk_l = pk_l.at[wflat].set(k_new[:, 0].astype(pk_l.dtype),
                                      mode="drop")
            pv_l = pv_l.at[wflat].set(v_new[:, 0].astype(pv_l.dtype),
                                      mode="drop")
            kf = jnp.take(pk_l, gidx, axis=0, mode="fill", fill_value=0)
            vf = jnp.take(pv_l, gidx, axis=0, mode="fill", fill_value=0)
        else:
            pk_l = pk_l.at[wflat].set(quantize_kv(k_new[:, 0], ks_l),
                                      mode="drop")
            pv_l = pv_l.at[wflat].set(quantize_kv(v_new[:, 0], vs_l),
                                      mode="drop")
            kf = dequantize_kv(
                jnp.take(pk_l, gidx, axis=0, mode="fill", fill_value=0),
                ks_l, dtype)
            vf = dequantize_kv(
                jnp.take(pv_l, gidx, axis=0, mode="fill", fill_value=0),
                vs_l, dtype)
        attn = dot_product_attention(q, kf, vf, bias, impl="auto")
        return _finish_layer(x, lp_, cfg, attn, dtype), (pk_l, pv_l)

    li = jnp.arange(cfg.num_layers)
    xs = (params["layers"], li, pk, pv)
    if kv_scales is not None:
        xs = xs + (kv_scales[0], kv_scales[1])
    x, (pk, pv) = jax.lax.scan(layer, x, xs, unroll=unroll)
    logits = lm_logits(params, head, cfg, x, dtype=dtype)[:, 0]
    return (logits, pk.reshape(pages_k.shape), pv.reshape(pages_v.shape))


def paged_chunk_step(params: Params, head: Params, cfg: BertConfig,
                     tokens: jax.Array,      # [B, T] int32 (suffix chunk)
                     pages_k: jax.Array,     # [L, P, page_sz, N, D]
                     pages_v: jax.Array,
                     page_table: jax.Array,  # [B, MP] int32 (sentinel P)
                     start: jax.Array,       # [B] absolute pos of tokens[:,0]
                     nreal: jax.Array,       # [B] real chunk lengths (0 ok)
                     *, kv_scales: Optional[Tuple[jax.Array,
                                                  jax.Array]] = None,
                     dtype=jnp.float32, unroll=True
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Suffix prefill against a paged cache: the prompt's SHARED prefix
    pages already hold K/V (a prefix-index hit), so only the divergent
    suffix runs — ``tokens[b, t]`` sits at absolute position ``start[b] +
    t``, writes through the page table, and attends to key positions
    ``<= start + t`` (shared prefix + the chunk's own causal triangle).
    Returns each row's LAST real token's next-token logits [B, vocab]
    (fp32), like :func:`prefill`.  Rows with ``nreal == 0`` are filler:
    their writes land OOB and their logits are garbage the caller
    discards."""
    _check_dense_trunk(params["layers"])
    L, P, ps = pages_k.shape[0], pages_k.shape[1], pages_k.shape[2]
    tail = pages_k.shape[3:]
    B, MP = page_table.shape
    T = tokens.shape[1]
    max_len = MP * ps
    start = start.astype(jnp.int32)
    nreal = nreal.astype(jnp.int32)
    positions = start[:, None] + jnp.arange(T, dtype=jnp.int32)  # [B, T]
    x, _ = bert.embed(params, cfg, tokens, jnp.zeros_like(tokens),
                      dtype=dtype, deterministic=True,
                      position_ids=positions)
    # per-query linear visibility: query t sees key j iff j <= start + t
    vis = (jnp.arange(max_len, dtype=jnp.int32)[None, None, :]
           <= positions[:, :, None])                     # [B, T, max_len]
    bias = jnp.where(vis, 0.0, -1e9).astype(jnp.float32)[:, None]
    gidx = _flat_gather_idx(page_table, ps)
    # write positions: padded chunk slots (t >= nreal) land OOB
    in_chunk = jnp.arange(T, dtype=jnp.int32)[None, :] < nreal[:, None]
    lp = jnp.clip(positions // ps, 0, MP - 1)
    phys = jnp.take_along_axis(page_table, lp, axis=1)   # [B, T]
    wflat = jnp.where(in_chunk & (phys < P) & (positions < max_len),
                      phys * ps + positions % ps, P * ps)
    pk = pages_k.reshape(L, P * ps, *tail)
    pv = pages_v.reshape(L, P * ps, *tail)

    def layer(carry, scanned):
        x = carry
        if kv_scales is None:
            lp_, _, pk_l, pv_l = scanned
        else:
            lp_, _, pk_l, pv_l, ks_l, vs_l = scanned
        q, k_new, v_new = _qkv(x, lp_, cfg, dtype)       # [B, T, N, D]
        if kv_scales is None:
            pk_l = pk_l.at[wflat].set(k_new.astype(pk_l.dtype),
                                      mode="drop")
            pv_l = pv_l.at[wflat].set(v_new.astype(pv_l.dtype),
                                      mode="drop")
            kf = jnp.take(pk_l, gidx, axis=0, mode="fill", fill_value=0)
            vf = jnp.take(pv_l, gidx, axis=0, mode="fill", fill_value=0)
        else:
            pk_l = pk_l.at[wflat].set(quantize_kv(k_new, ks_l),
                                      mode="drop")
            pv_l = pv_l.at[wflat].set(quantize_kv(v_new, vs_l),
                                      mode="drop")
            kf = dequantize_kv(
                jnp.take(pk_l, gidx, axis=0, mode="fill", fill_value=0),
                ks_l, dtype)
            vf = dequantize_kv(
                jnp.take(pv_l, gidx, axis=0, mode="fill", fill_value=0),
                vs_l, dtype)
        attn = dot_product_attention(q, kf, vf, bias, impl="auto")
        return _finish_layer(x, lp_, cfg, attn, dtype), (pk_l, pv_l)

    li = jnp.arange(cfg.num_layers)
    xs = (params["layers"], li, pk, pv)
    if kv_scales is not None:
        xs = xs + (kv_scales[0], kv_scales[1])
    x, (pk, pv) = jax.lax.scan(layer, x, xs, unroll=unroll)
    last = jnp.clip(nreal - 1, 0, T - 1)
    h_last = jnp.take_along_axis(x, last[:, None, None], axis=1)  # [B,1,H]
    logits = lm_logits(params, head, cfg, h_last, dtype=dtype)[:, 0]
    return (logits, pk.reshape(pages_k.shape), pv.reshape(pages_v.shape))


def paged_verify_step(params: Params, head: Params, cfg: BertConfig,
                      tokens: jax.Array,      # [B, K1] int32 (spec window)
                      pages_k: jax.Array,     # [L, P, page_sz, N, D]
                      pages_v: jax.Array,
                      page_table: jax.Array,  # [B, MP] int32 (sentinel P)
                      start: jax.Array,       # [B] abs pos of tokens[:,0]
                      nreal: jax.Array,       # [B] real window lengths
                      *, kv_scales: Optional[Tuple[jax.Array,
                                                   jax.Array]] = None,
                      dtype=jnp.float32, unroll=True
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Speculative verify: score the pending token plus k drafted tokens
    in ONE prefill-shaped call against the primary's paged cache.  The
    body is :func:`paged_chunk_step` verbatim — same per-query linear
    visibility, same write-through-the-table K/V commit — but the LM
    head runs over EVERY window position, returning ``[B, K1, vocab]``
    fp32 so the caller can take the greedy target at each draft offset.
    K/V for the whole window is written eagerly; rejected positions stay
    in the cache as stale entries that no later query can see (the
    visibility mask is position-based) and the next round overwrites
    them in place.  Rows with ``nreal == 0`` are filler whose writes
    land OOB (sentinel table rows) and whose logits the caller
    discards."""
    _check_dense_trunk(params["layers"])
    L, P, ps = pages_k.shape[0], pages_k.shape[1], pages_k.shape[2]
    tail = pages_k.shape[3:]
    B, MP = page_table.shape
    T = tokens.shape[1]
    max_len = MP * ps
    start = start.astype(jnp.int32)
    nreal = nreal.astype(jnp.int32)
    positions = start[:, None] + jnp.arange(T, dtype=jnp.int32)  # [B, K1]
    x, _ = bert.embed(params, cfg, tokens, jnp.zeros_like(tokens),
                      dtype=dtype, deterministic=True,
                      position_ids=positions)
    vis = (jnp.arange(max_len, dtype=jnp.int32)[None, None, :]
           <= positions[:, :, None])                    # [B, K1, max_len]
    bias = jnp.where(vis, 0.0, -1e9).astype(jnp.float32)[:, None]
    gidx = _flat_gather_idx(page_table, ps)
    in_chunk = jnp.arange(T, dtype=jnp.int32)[None, :] < nreal[:, None]
    lp = jnp.clip(positions // ps, 0, MP - 1)
    phys = jnp.take_along_axis(page_table, lp, axis=1)   # [B, K1]
    wflat = jnp.where(in_chunk & (phys < P) & (positions < max_len),
                      phys * ps + positions % ps, P * ps)
    pk = pages_k.reshape(L, P * ps, *tail)
    pv = pages_v.reshape(L, P * ps, *tail)

    def layer(carry, scanned):
        x = carry
        if kv_scales is None:
            lp_, _, pk_l, pv_l = scanned
        else:
            lp_, _, pk_l, pv_l, ks_l, vs_l = scanned
        q, k_new, v_new = _qkv(x, lp_, cfg, dtype)       # [B, K1, N, D]
        if kv_scales is None:
            pk_l = pk_l.at[wflat].set(k_new.astype(pk_l.dtype),
                                      mode="drop")
            pv_l = pv_l.at[wflat].set(v_new.astype(pv_l.dtype),
                                      mode="drop")
            kf = jnp.take(pk_l, gidx, axis=0, mode="fill", fill_value=0)
            vf = jnp.take(pv_l, gidx, axis=0, mode="fill", fill_value=0)
        else:
            pk_l = pk_l.at[wflat].set(quantize_kv(k_new, ks_l),
                                      mode="drop")
            pv_l = pv_l.at[wflat].set(quantize_kv(v_new, vs_l),
                                      mode="drop")
            kf = dequantize_kv(
                jnp.take(pk_l, gidx, axis=0, mode="fill", fill_value=0),
                ks_l, dtype)
            vf = dequantize_kv(
                jnp.take(pv_l, gidx, axis=0, mode="fill", fill_value=0),
                vs_l, dtype)
        attn = dot_product_attention(q, kf, vf, bias, impl="auto")
        return _finish_layer(x, lp_, cfg, attn, dtype), (pk_l, pv_l)

    li = jnp.arange(cfg.num_layers)
    xs = (params["layers"], li, pk, pv)
    if kv_scales is not None:
        xs = xs + (kv_scales[0], kv_scales[1])
    x, (pk, pv) = jax.lax.scan(layer, x, xs, unroll=unroll)
    logits = lm_logits(params, head, cfg, x, dtype=dtype)   # [B, K1, V]
    return (logits, pk.reshape(pages_k.shape), pv.reshape(pages_v.shape))


# ------------------------------------------------------- infilling scoring

def infill_logits(params: Params, head: Params, cfg: BertConfig,
                  input_ids: jax.Array,       # [B, S] int32
                  attention_mask: jax.Array,  # [B, S] {0,1}
                  *, dtype=jnp.float32, attn_impl: str = "auto",
                  unroll=True) -> jax.Array:
    """MLM-infilling scorer: the BIDIRECTIONAL trunk (BERT's native
    objective — no causal mask) + the LM head over every position,
    [B, S, vocab] fp32.  The serve engine reads the rows at ``[MASK]``
    positions; everything (trunk, head, tied decoder) is shared with the
    causal path, so one checkpoint answers both "continue this" and
    "fill this in"."""
    zeros = jnp.zeros_like(input_ids)
    hidden = bert.encode(params, cfg, input_ids, zeros, attention_mask,
                         dtype=dtype, deterministic=True,
                         attn_impl=attn_impl, unroll=unroll)
    return lm_logits(params, head, cfg, hidden, dtype=dtype)


# ------------------------------------------------------------- calibration

def kv_cache_bytes(cfg: BertConfig, slots: int, max_len: int,
                   kv_dtype) -> int:
    """Preallocated K+V cache bytes for a slot block — the number the
    ``--kv_hbm_mb`` budget (obs.memory.KVBudget) is checked against."""
    itemsize = np.dtype(kv_dtype).itemsize
    return int(2 * cfg.num_layers * slots * max_len * cfg.hidden_size
               * itemsize)


def calibrate_kv_scales(params: Params, cfg: BertConfig, *,
                        seq_len: Optional[int] = None,
                        dtype=jnp.float32
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-(layer, head, channel) symmetric int8 K/V scale tables
    ``[L, N, D]`` from a SEEDED synthetic causal forward — no corpus, no
    device requirement, and deterministic in the params alone, so the
    offline artifact (``scripts/quantize_ckpt.py --kv_calib``) and engine
    self-calibration at warmup produce byte-identical tables."""
    seq_len = int(seq_len or min(128, cfg.max_position))
    # a raw host tree (the offline script's load_raw) must compute through
    # the SAME backend as device params — numpy operands would dispatch
    # numpy's BLAS on the first matmul and the tables would drift by ULPs
    params = jax.tree_util.tree_map(jnp.asarray, params)
    key = jax.random.key(CALIB_SEED)
    ids = jax.random.randint(key, (CALIB_ROWS, seq_len), 0, cfg.vocab_size,
                             dtype=jnp.int32)
    mask = jnp.ones((CALIB_ROWS, seq_len), jnp.int32)
    x, _ = bert.embed(params, cfg, ids, jnp.zeros_like(ids), dtype=dtype,
                      deterministic=True)
    _, ks, vs = run_layers_kv(params["layers"], cfg, x,
                              bias=mask_bias(mask, jnp.float32),
                              causal=True, dtype=dtype)
    # amax over (rows, positions) -> [L, N, D]; zero channels get scale 1
    def table(t):
        amax = np.abs(np.asarray(t, np.float32)).max(axis=(1, 2))
        return np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)

    return table(ks), table(vs)
