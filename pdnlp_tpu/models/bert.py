"""Functional BERT encoder + sequence-classification head (pure JAX).

Capability twin of the reference's HF ``BertForSequenceClassification``
(``/root/reference/single-gpu-cls.py:252-255``: BERT-base, ``num_labels=6``,
forward ``(input_ids, token_type_ids, attention_mask)`` -> logits), but the
implementation is TPU-native rather than a port:

- **params are a plain pytree** (nested dicts of ``jnp`` arrays) — no module
  system.  This makes per-leaf ``NamedSharding`` (ZeRO/tensor sharding),
  donation, and checkpointing trivial.
- **one ``lax.scan`` over stacked layers**: every transformer layer's weights
  carry a leading ``[L, ...]`` axis and the 12 layers run as a single traced
  step — compile time stays flat in depth and XLA pipelines HBM prefetch of
  layer ``i+1`` against compute of layer ``i``.
- **mixed precision by policy**: master params live in fp32; ``dtype``
  selects the compute precision (bf16 = the AMP analog,
  ``/root/reference/multi-gpu-distributed-mp-amp-cls.py:160-175``).  Softmax
  and LayerNorm reduce in fp32; logits return in fp32.
- **remat**: ``remat=True`` wraps the scanned layer body in
  ``jax.checkpoint`` (the activation-checkpointing analog of
  ``/root/reference/multi-gpu-deepspeed-cls.py:240-244``).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from pdnlp_tpu.models.config import BertConfig
from pdnlp_tpu.ops.attention import dot_product_attention, mask_bias

Params = Dict[str, Any]


def _fuse_qkv() -> bool:
    """Whether attention computes q/k/v as ONE fused [H, 3H] matmul.

    Trace-time switch (``PDNLP_FUSE_QKV``), default OFF: the fused form is
    the textbook win on GPU, but on v5e it measured 3% SLOWER than three
    separate projections (33.1 -> 32.0 probe steps/s — XLA materializes the
    weight concat each step instead of folding it; results/profile_r05.json)
    and the split form keeps tp's per-tensor output sharding natural.  The
    path stays for A/B profiling on other TPU generations."""
    import os

    return os.environ.get("PDNLP_FUSE_QKV", "0") == "1"


def _gelu(x, form: str = "erf"):
    """GELU — ``form`` comes from ``cfg.gelu`` at every call site.

    ``"erf"`` is the exact form — the reference BERT's activation
    (``transformers`` ``hidden_act="gelu"``).  ``"tanh"`` trades the erf
    backward (a VPU transcendental chain the step profile priced at
    ~3.3 ms — ``results/profile_r05.json`` "exact-GELU backward") for a
    cheaper polynomial; max |Δ| vs erf is ~4e-4, and the shipped recipe
    measured +7% step rate AND +0.7pt fine-tune accuracy when pretrained
    with it end to end (0.5887 vs erf's 0.5813 — bench.py recipe note).
    ``PDNLP_GELU_TANH=1`` force-enables tanh regardless of config — the
    A/B profiling override (``scripts/profile_step.py``)."""
    import os

    if form not in ("erf", "tanh"):
        # loud: a typo'd --gelu would otherwise silently run erf while
        # bench.py keys its pretrain cache on the raw string
        raise ValueError(f"gelu must be 'erf' or 'tanh', got {form!r}")
    approx = form == "tanh" or os.environ.get("PDNLP_GELU_TANH", "0") == "1"
    return jax.nn.gelu(x, approximate=approx)


# --------------------------------------------------------------------------
# init
# --------------------------------------------------------------------------

def _dense_init(key, fan_in: int, fan_out: int, std: float, stacked: int = 0):
    shape = (fan_in, fan_out) if not stacked else (stacked, fan_in, fan_out)
    k = jax.random.truncated_normal(key, -2.0, 2.0, shape, jnp.float32) * std
    b = jnp.zeros(shape[:-2] + (fan_out,), jnp.float32)
    return {"kernel": k, "bias": b}


def _ln_init(width: int, stacked: int = 0):
    shape = (stacked, width) if stacked else (width,)
    return {"scale": jnp.ones(shape, jnp.float32), "bias": jnp.zeros(shape, jnp.float32)}


def init_params(key: jax.Array, cfg: BertConfig) -> Params:
    """Build the parameter pytree (fp32 masters), truncated-normal 0.02."""
    H, L, I, std = cfg.hidden_size, cfg.num_layers, cfg.intermediate_size, cfg.initializer_range
    keys = jax.random.split(key, 12)

    def emb(k, rows):
        return jax.random.truncated_normal(k, -2.0, 2.0, (rows, H), jnp.float32) * std

    layers = {
        # all per-layer weights stacked on a leading [L] axis for lax.scan
        "q": _dense_init(keys[3], H, H, std, L),
        "k": _dense_init(keys[4], H, H, std, L),
        "v": _dense_init(keys[5], H, H, std, L),
        "o": _dense_init(keys[6], H, H, std, L),
        "attn_ln": _ln_init(H, L),
        "mlp_ln": _ln_init(H, L),
    }
    if not cfg.moe_experts:
        layers["up"] = _dense_init(keys[7], H, I, std, L)
        layers["down"] = _dense_init(keys[8], I, H, std, L)
    else:
        # MLP becomes E gated experts: weights gain an expert dim after the
        # layer dim ([L, E, in, out]) so the "ep" sharding mode can split
        # dim 1 over an "expert" mesh axis
        E = cfg.moe_experts

        def expert_dense(k, fan_in, fan_out):
            kk = jax.random.truncated_normal(
                k, -2.0, 2.0, (L, E, fan_in, fan_out), jnp.float32) * std
            return {"kernel": kk,
                    "bias": jnp.zeros((L, E, fan_out), jnp.float32)}

        layers["up"] = expert_dense(keys[7], H, I)
        layers["down"] = expert_dense(keys[8], I, H)
        layers["gate"] = {"kernel": jax.random.truncated_normal(
            keys[11], -2.0, 2.0, (L, H, E), jnp.float32) * std}
    return {
        "embeddings": {
            "word": emb(keys[0], cfg.vocab_size),
            "position": emb(keys[1], cfg.max_position),
            "token_type": emb(keys[2], cfg.type_vocab_size),
            "ln": _ln_init(H),
        },
        "layers": layers,
        "pooler": _dense_init(keys[9], H, H, std),
        "classifier": _dense_init(keys[10], H, cfg.num_labels, std),
    }


def param_count(params: Params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _layer_norm(x, scale, bias, eps):
    # reduce in fp32 whatever the compute dtype
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _dense(x, p, dtype):
    if "qscale" in p:
        # int8 weight-only serving (serve.quant): the per-OUTPUT-channel
        # scale commutes through the contraction, so it multiplies the
        # [.., out] RESULT — the int8 kernel is the only weight HBM reads,
        # and no dequantized copy materializes
        y = x @ p["kernel"].astype(dtype)
        return y * p["qscale"].astype(dtype) + p["bias"].astype(dtype)
    return x @ p["kernel"].astype(dtype) + p["bias"].astype(dtype)


def _expert_scale(p, y, dtype):
    """int8 serving (``serve.quant``): per-output-channel scale applied to
    an expert einsum OUTPUT ``[E, ..., out]`` — the same commute as
    ``_dense``, which never sees the MoE expert layouts.  Identity for
    float params."""
    if "qscale" not in p:
        return y
    s = p["qscale"].astype(dtype)                      # [E, out]
    return y * s.reshape(s.shape[0], *([1] * (y.ndim - 2)), s.shape[-1])


def _dropout(x, rate, key):
    if rate <= 0.0:  # trace-time constant: rate-0 configs skip mask codegen
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def encode(
    params: Params,
    cfg: BertConfig,
    input_ids: jax.Array,        # [B, S] int32
    token_type_ids: jax.Array,   # [B, S] int32
    attention_mask: jax.Array,   # [B, S] {0,1}
    *,
    dtype=jnp.float32,
    deterministic: bool = True,
    rng: Optional[jax.Array] = None,
    remat: bool = False,
    attn_impl: str = "auto",
    seq_axis: Optional[str] = None,
    attn_bias: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    position_ids: Optional[jax.Array] = None,
    unroll=True,
    with_aux: bool = False,
) -> jax.Array:
    """Run the encoder stack; returns hidden states [B, S, H] in ``dtype``
    (or ``(hidden, moe_aux)`` under ``with_aux`` — see ``run_layers``).

    ``unroll``: ``lax.scan`` unroll factor over the stacked layers.  Full
    unroll (``True``) measured 14% faster per fused train step on v5e than
    the rolled scan (27.7 vs 32.3 ms at batch 32/seq 128) — XLA regains
    per-layer layout/fusion freedom; ``1`` keeps compile time flat in
    depth.

    ``seq_axis``: name of a mesh axis the *sequence* dimension is sharded
    over (must be inside ``shard_map``).  Position embeddings use global
    positions (shard offset) and attention runs as ring attention over the
    axis (``ops.ring``) — the long-context sequence-parallel path.

    ``attn_bias``: optional additive bias broadcastable to [B, N, S, S]
    that *replaces* the mask-derived bias (an explicit pre-built mask;
    always the XLA-style additive contract).

    ``segment_ids``: [B, S] packed-row segment IDs (0 = padding) — the
    preferred packed-path mask input: the block-diagonal mask is ROUTED,
    not materialized here.  A pallas-routed attention computes it inside
    the kernel (``ops.flash``); the XLA fallback builds
    ``data.packing.segment_bias`` inside ``ops.attention``; under
    ``seq_axis`` the sharded IDs ride the ring and each hop masks its
    own shard-local block (``ops.ring``).  On every route this module
    never holds the [B, 1, S, S] bias.

    ``position_ids``: optional explicit [B, S] position-embedding indices
    (packed rows restart positions per segment); default is the row
    position ``arange(S)`` every unpacked batch uses.
    """
    B, S = input_ids.shape
    shard_offset = 0
    if seq_axis is not None:
        from pdnlp_tpu.parallel.compat import axis_size

        shard_offset = jax.lax.axis_index(seq_axis) * S
        if position_ids is None and S * axis_size(seq_axis) > cfg.max_position:
            raise ValueError("global sequence exceeds max_position")
    elif position_ids is None and S > cfg.max_position:
        # explicit position_ids (packed rows restart per segment) carry
        # their own bound — the longest SEGMENT, validated at setup
        # (data.sampler.validate_length_buckets); rows may be wider than
        # the table, that is the packed long-context payoff
        raise ValueError(
            f"sequence length {S} exceeds max_position {cfg.max_position}; "
            "JAX gather would silently clamp position embeddings")
    x, rng = embed(params, cfg, input_ids, token_type_ids, dtype=dtype,
                   deterministic=deterministic, rng=rng,
                   shard_offset=shard_offset, position_ids=position_ids)

    ring_bias = bias = None
    if attn_bias is not None:
        if seq_axis is not None:
            raise ValueError("attn_bias overrides are not supported on the "
                             "sequence-parallel (ring attention) path")
        if segment_ids is not None:
            raise ValueError("pass attn_bias OR segment_ids, not both — "
                             "the packed mask rides the IDs (padding is "
                             "segment 0), an explicit bias replaces it")
        bias = attn_bias.astype(dtype)
    elif segment_ids is not None:
        # bias stays None on EVERY route — the mask rides the IDs: in-kernel
        # on pallas, segment_bias inside ops.attention on XLA, per-hop
        # shard-local blocks on the ring (ops.ring receives the sharded IDs)
        pass
    elif seq_axis is None:
        bias = mask_bias(attention_mask, dtype)
    else:
        # same additive-mask semantics, squeezed to the [B, S_local] rows the
        # ring rotates alongside KV
        ring_bias = mask_bias(attention_mask, jnp.float32)[:, 0, 0, :]
    return run_layers(
        params["layers"], cfg, x, li=jnp.arange(cfg.num_layers), bias=bias,
        ring_bias=ring_bias, dtype=dtype, deterministic=deterministic,
        rng=rng, remat=remat, attn_impl=attn_impl, seq_axis=seq_axis,
        segment_ids=segment_ids, unroll=unroll, with_aux=with_aux,
        token_mask=attention_mask,
    )


def embed(params: Params, cfg: BertConfig, input_ids: jax.Array,
          token_type_ids: jax.Array, *, dtype=jnp.float32,
          deterministic: bool = True, rng: Optional[jax.Array] = None,
          shard_offset=0, position_ids: Optional[jax.Array] = None):
    """Embedding sum + LayerNorm + dropout; returns ``(x, rng)`` with the
    embedding dropout's split consumed, so layer streams continue from the
    returned key exactly as they did when this lived inline in ``encode``.
    Public so the pipeline-parallel path can run it on its first stage.
    ``position_ids`` overrides the row-position ``arange`` (packed rows
    restart positions per segment)."""
    S = input_ids.shape[1]
    emb = params["embeddings"]
    pos = (emb["position"][position_ids] if position_ids is not None
           else emb["position"][jnp.arange(S) + shard_offset])
    x = (
        emb["word"][input_ids]
        + pos
        + emb["token_type"][token_type_ids]
    ).astype(dtype)
    x = _layer_norm(x, emb["ln"]["scale"], emb["ln"]["bias"], cfg.layer_norm_eps)
    if not deterministic:
        rng, k = jax.random.split(rng)
        x = _dropout(x, cfg.dropout, k)
    return x, rng


def run_layers(layers: Params, cfg: BertConfig, x: jax.Array, *,
               li: jax.Array, bias: Optional[jax.Array] = None,
               ring_bias: Optional[jax.Array] = None, dtype=jnp.float32,
               deterministic: bool = True, rng: Optional[jax.Array] = None,
               remat: bool = False, attn_impl: str = "auto",
               seq_axis: Optional[str] = None,
               segment_ids: Optional[jax.Array] = None, unroll=True,
               with_aux: bool = False, token_mask: Optional[jax.Array] = None):
    """Scan a stacked slice of encoder layers over ``x`` ([B, S, H]).

    ``layers`` holds leading-dim-stacked weights (any contiguous slice of
    the stack) and ``li`` the matching *global* layer indices — dropout
    streams key on the global index, so a pipeline stage running layers
    [k..2k) reproduces exactly the streams the full stack would.  Public so
    the pipeline-parallel path can run per-stage slices.

    A ``gate`` tree marks MoE layers (``cfg.moe_experts``): the MLP becomes
    top-k gated experts and the scan additionally accumulates the
    load-balancing auxiliary loss — pass ``with_aux=True`` to receive
    ``(x, aux)`` (training needs it; eval may drop it)."""
    B, S = x.shape[0], x.shape[1]
    N, D = cfg.num_heads, cfg.head_dim
    moe = "gate" in layers
    if moe and seq_axis is not None:
        raise ValueError("MoE layers are not supported on the "
                         "sequence-parallel (ring attention) path")

    def attn_block(x, lp, idx, rng):
        def heads(t):
            return t.reshape(B, S, N, D)

        if _fuse_qkv() and "qscale" not in lp["q"]:
            # (int8 params skip the fused form: concatenating quantized
            # kernels would drop their per-channel scales)
            # one [H, 3H] projection: x is read from HBM once instead of
            # three times and XLA tiles a single larger MXU matmul.  Params
            # stay stored as separate q/k/v trees (checkpoint + tp-sharding
            # compatibility); the concat below is trace-time weight reshaping
            # that XLA folds into the matmul's operand layout.
            w = jnp.concatenate([lp["q"]["kernel"], lp["k"]["kernel"],
                                 lp["v"]["kernel"]], -1).astype(dtype)
            bqkv = jnp.concatenate([lp["q"]["bias"], lp["k"]["bias"],
                                    lp["v"]["bias"]], -1).astype(dtype)
            q, k, v = (heads(t) for t in jnp.split(x @ w + bqkv, 3, -1))
        else:
            q = heads(_dense(x, lp["q"], dtype))
            k = heads(_dense(x, lp["k"], dtype))
            v = heads(_dense(x, lp["v"], dtype))
        if seq_axis is not None:
            from pdnlp_tpu.ops.ring import ring_attention

            attn = ring_attention(
                q, k, v, ring_bias, axis_name=seq_axis,
                dropout_rate=0.0 if deterministic else cfg.attn_dropout,
                dropout_rng=None if deterministic else jax.random.fold_in(rng, 3 * idx + 2),
                segment_ids=segment_ids,
            )
        else:
            attn = dot_product_attention(
                q, k, v, bias, impl=attn_impl,
                dropout_rate=0.0 if deterministic else cfg.attn_dropout,
                dropout_rng=None if deterministic else jax.random.fold_in(rng, 3 * idx + 2),
                segment_ids=segment_ids,
            )
        attn = _dense(attn.reshape(B, S, N * D), lp["o"], dtype)
        if not deterministic:
            attn = _dropout(attn, cfg.dropout, jax.random.fold_in(rng, 3 * idx))
        return _layer_norm(x + attn, lp["attn_ln"]["scale"], lp["attn_ln"]["bias"],
                           cfg.layer_norm_eps)

    def mlp_out(x, lp, idx, rng, h):
        if not deterministic:
            h = _dropout(h, cfg.dropout, jax.random.fold_in(rng, 3 * idx + 1))
        return _layer_norm(x + h, lp["mlp_ln"]["scale"], lp["mlp_ln"]["bias"],
                           cfg.layer_norm_eps)

    def layer(carry, scanned):
        x, rng = carry
        lp, idx = scanned
        x = attn_block(x, lp, idx, rng)
        h = _gelu(_dense(x, lp["up"], dtype), cfg.gelu)
        h = _dense(h, lp["down"], dtype)
        x = mlp_out(x, lp, idx, rng, h)
        return (x, rng), None

    def layer_moe(carry, scanned):
        x, rng, aux = carry
        lp, idx = scanned
        x = attn_block(x, lp, idx, rng)
        h, a = moe_mlp(x, lp, cfg, dtype=dtype, mask=token_mask)
        x = mlp_out(x, lp, idx, rng, h)
        return (x, rng, aux + a), None

    body = layer_moe if moe else layer
    if remat:
        body = jax.checkpoint(body)

    if rng is None:
        rng = jax.random.key(0)  # unused when deterministic
    if moe:
        (x, _, aux), _ = jax.lax.scan(
            body, (x, rng, jnp.zeros((), jnp.float32)), (layers, li),
            unroll=unroll)
    else:
        (x, _), _ = jax.lax.scan(body, (x, rng), (layers, li), unroll=unroll)
        aux = jnp.zeros((), jnp.float32)
    return (x, aux) if with_aux else x


def moe_mlp(x: jax.Array, lp: Params, cfg: BertConfig, *, dtype=jnp.float32,
            mask: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
    """Top-k gated mixture-of-experts MLP, one layer.

    Routing (shared by both dispatches): fp32 softmax gate, top-k experts
    per token, renormalized combine weights, Switch-style load-balancing
    aux loss E * sum_e(token_frac_e * prob_frac_e) (caller accumulates;
    1.0 = perfectly balanced).  ``mask`` ([B, S] {0,1}) keeps padding out
    of the balancing statistics — and, under grouped dispatch, out of the
    capacity slots — without it, padding (identical embeddings routed
    identically) dilutes the pressure on real tokens by the padding
    fraction.

    ``cfg.moe_dispatch`` picks the compute:

    - ``"grouped"`` (default): capacity-based dispatch — gather each
      expert's tokens into a static ``[E, capacity, H]`` buffer, run the
      expert FFNs as batched matmuls, scatter-combine.  FFN cost scales
      with ``k * capacity_factor``, not ``E`` (the property that makes
      expert counts beyond a handful affordable); tokens over a full
      expert's capacity skip that expert (the residual connection carries
      them — standard Switch/GShard semantics).
    - ``"dense"``: every expert computes every token and the gate-weighted
      combine contracts the expert dim (the GSPMD formulation; exact — no
      capacity drops — and the parity oracle for the grouped path, but
      O(E) FLOPs: measured 11.7 vs 35.5 dense-model steps/s at E=4 on
      v5e, r4 matrix).

    Under the "ep" sharding mode the expert dim of the weights (and of the
    grouped path's ``[E, capacity, H]`` buffers) is split over an "expert"
    mesh axis; XLA inserts the combine all-reduce from the shardings.

    Returns ``(output [B,S,H], aux)``.
    """
    E = lp["gate"]["kernel"].shape[-1]
    gate_logits = (x @ lp["gate"]["kernel"].astype(dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(gate_logits)                      # [B,S,E] fp32
    k = min(cfg.moe_top_k, E)
    top_p, top_idx = jax.lax.top_k(probs, k)                 # [B,S,k]
    # renormalized top-k combine weights
    renorm = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)

    if cfg.moe_dispatch not in ("grouped", "dense"):
        raise ValueError(
            f"moe_dispatch={cfg.moe_dispatch!r} — use 'grouped' or 'dense'; "
            "a silent fallback would quietly benchmark the O(E) path")
    if cfg.moe_dispatch == "grouped":
        out = _moe_grouped(x, lp, top_idx, renorm, cfg, dtype=dtype,
                           mask=mask)
    else:
        onehot = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)  # [B,S,k,E]
        combine = jnp.einsum("bske,bsk->bse", onehot, renorm)   # [B,S,E]
        up_k, up_b = lp["up"]["kernel"], lp["up"]["bias"]    # [E,H,I],[E,I]
        down_k, down_b = lp["down"]["kernel"], lp["down"]["bias"]
        h = _expert_scale(lp["up"],
                          jnp.einsum("bsh,ehi->ebsi", x, up_k.astype(dtype)),
                          dtype) + up_b.astype(dtype)[:, None, None, :]
        h = _gelu(h, cfg.gelu)
        y = _expert_scale(lp["down"],
                          jnp.einsum("ebsi,eih->ebsh", h, down_k.astype(dtype)),
                          dtype) + down_b.astype(dtype)[:, None, None, :]
        out = jnp.einsum("ebsh,bse->bsh", y, combine.astype(dtype))

    # Switch load-balancing statistics (masked means: see docstring)
    top1 = jax.nn.one_hot(top_idx[..., 0], E, dtype=jnp.float32)
    if mask is not None:
        m = mask.astype(jnp.float32).reshape(-1)[:, None]     # [BS, 1]
        denom = jnp.maximum(m.sum(), 1.0)
        token_frac = (top1.reshape(-1, E) * m).sum(0) / denom
        prob_frac = (probs.reshape(-1, E) * m).sum(0) / denom
    else:
        token_frac = top1.reshape(-1, E).mean(0)
        prob_frac = probs.reshape(-1, E).mean(0)
    aux = E * jnp.sum(token_frac * prob_frac)
    return out, aux


def _moe_grouped(x: jax.Array, lp: Params, top_idx: jax.Array,
                 renorm: jax.Array, cfg: BertConfig, *, dtype,
                 mask: Optional[jax.Array]) -> jax.Array:
    """Capacity-based expert dispatch: static shapes end to end.

    Slot assignment is the GShard position-in-expert cumsum: assignments
    are ranked token-major (earlier tokens win capacity), each keeps its
    slot iff ``position < capacity``.  Dropped assignments simply don't
    contribute (the caller's residual carries the token).  Padding tokens
    (``mask`` 0) never occupy slots — on this corpus ~80% of positions are
    padding, which would otherwise eat most of the capacity real tokens
    need.  With ``capacity >= tokens`` nothing can drop and the result
    equals dense dispatch up to summation order (pinned in
    ``tests/test_moe.py``)."""
    import math

    B, S, H = x.shape
    T = B * S
    E = lp["up"]["kernel"].shape[0]
    k = top_idx.shape[-1]
    C = int(math.ceil(cfg.moe_capacity_factor * k * T / E))
    C = min(C, T)  # one slot per token per expert is the most ever needed

    x2 = x.reshape(T, H)
    flat_e = top_idx.reshape(-1)                      # [T*k], token-major
    w_flat = renorm.reshape(-1)                       # [T*k] fp32
    keep = jnp.ones((T * k,), bool)
    if mask is not None:
        keep = jnp.repeat(mask.reshape(-1).astype(bool), k)
    # position-in-expert: how many kept assignments to my expert precede me
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32) * keep[:, None]
    pos = jnp.cumsum(onehot, axis=0) - onehot         # [T*k, E]
    mypos = jnp.take_along_axis(pos, flat_e[:, None], 1)[:, 0]
    keep = keep & (mypos < C)
    # slot tables: [E, C] -> source token (sentinel T = zero row) + weight
    e_idx = jnp.where(keep, flat_e, E)                # E = out of bounds
    tok = jnp.arange(T * k, dtype=jnp.int32) // k
    slot_tok = jnp.full((E, C), T, jnp.int32).at[e_idx, mypos].set(
        tok, mode="drop")
    slot_w = jnp.zeros((E, C), jnp.float32).at[e_idx, mypos].set(
        w_flat, mode="drop")

    xe = jnp.concatenate([x2, jnp.zeros((1, H), x2.dtype)])[slot_tok]
    h = _expert_scale(
        lp["up"],
        jnp.einsum("ech,ehi->eci", xe, lp["up"]["kernel"].astype(dtype)),
        dtype) + lp["up"]["bias"].astype(dtype)[:, None, :]
    h = _gelu(h, cfg.gelu)
    y = _expert_scale(
        lp["down"],
        jnp.einsum("eci,eih->ech", h, lp["down"]["kernel"].astype(dtype)),
        dtype) + lp["down"]["bias"].astype(dtype)[:, None, :]
    y = y * slot_w[..., None].astype(dtype)           # sentinel slots -> 0
    out = jnp.zeros((T + 1, H), dtype).at[slot_tok.reshape(-1)].add(
        y.reshape(E * C, H), mode="drop")[:T]
    return out.reshape(B, S, H)


def init_mlm_head(key: jax.Array, cfg: BertConfig) -> Params:
    """Masked-LM head params (kept as a SEPARATE tree so classification
    checkpoints and the fine-tune model never carry it): dense transform +
    LayerNorm, then a decoder TIED to the word-embedding matrix plus a
    per-token output bias — the standard BERT MLM head, which the reference
    never needs because it downloads already-pretrained weights
    (``/root/reference/single-gpu-cls.py:252``)."""
    H = cfg.hidden_size
    return {
        "transform": _dense_init(key, H, H, cfg.initializer_range),
        "ln": _ln_init(H),
        "bias": jnp.zeros((cfg.vocab_size,), jnp.float32),
    }


def mlm_logits(params: Params, head: Params, cfg: BertConfig,
               hidden: jax.Array, *, dtype=jnp.float32) -> jax.Array:
    """[B, S, H] encoder output -> [B, S, vocab] logits (fp32).

    The decoder weight is ``params['embeddings']['word']`` transposed (weight
    tying): on a corpus this small the embedding table gets gradient signal
    from every masked position, not just from input lookups."""
    h = _gelu(_dense(hidden, head["transform"], dtype), cfg.gelu)
    h = _layer_norm(h, head["ln"]["scale"], head["ln"]["bias"], cfg.layer_norm_eps)
    word = params["embeddings"]["word"].astype(dtype)
    logits = jnp.einsum("bsh,vh->bsv", h, word) + head["bias"].astype(dtype)
    return logits.astype(jnp.float32)


def classify(
    params: Params,
    cfg: BertConfig,
    batch: Dict[str, jax.Array],
    *,
    dtype=jnp.float32,
    deterministic: bool = True,
    rng: Optional[jax.Array] = None,
    remat: bool = False,
    attn_impl: str = "auto",
    seq_axis: Optional[str] = None,
    unroll=True,
    return_aux: bool = False,
    return_pooled: bool = False,
) -> jax.Array:
    """Logits [B, num_labels] (fp32) — the ``model(**batch) -> logits`` twin
    of the reference's classification forward (``single-gpu-cls.py:119-124``:
    pooled [CLS] -> dropout -> linear).  ``return_aux`` additionally returns
    the MoE load-balancing loss (0 for dense models).

    Under ``seq_axis`` (sequence-parallel), the [CLS] position lives on
    shard 0; a masked ``psum`` broadcasts it so every shard computes the
    same logits.  Attention-probability dropout runs per ring block
    (``ops.ring``) — same distribution as the dense path, shard-layout-
    dependent draws.

    A PACKED batch (``--length_mode pack``: ``segment_ids`` +
    ``cls_positions`` channels, ``data.packing.PackedClassificationDataset``)
    carries several examples per row: attention applies the block-diagonal
    segment mask so examples never cross-attend (in-kernel from
    ``segment_ids`` on the pallas route; ``data.packing.segment_bias``
    built inside ``ops.attention`` on the XLA fallback — this function
    never materializes it), each segment's [CLS] hidden state is gathered
    at its ``cls_positions`` offset, and the head returns per-SEGMENT
    logits ``[B, M, num_labels]`` (labels/weights in the batch are
    ``[B, M]`` to match) — per-example semantics, packed compute.  The
    batch-key check is trace-static (dict structure, not values): packed
    and unpacked batches are separate compiled programs.

    ``return_pooled``: return the pooled PRE-classifier features
    ([B, H] / packed [B, M, H], tanh + dropout applied) instead of logits
    — the input contract of the fused projection+CE kernel
    (``ops.fused_ce``), which consumes the classifier weights itself."""
    packed = "cls_positions" in batch
    if not deterministic:
        rng, enc_rng, drop_rng = jax.random.split(rng, 3)
    else:
        enc_rng = drop_rng = None
    hidden, aux = encode(
        params, cfg,
        batch["input_ids"], batch["token_type_ids"], batch["attention_mask"],
        dtype=dtype, deterministic=deterministic, rng=enc_rng, remat=remat,
        attn_impl=attn_impl, seq_axis=seq_axis,
        segment_ids=batch["segment_ids"] if packed else None,
        position_ids=batch.get("position_ids") if packed else None,
        unroll=unroll, with_aux=True,
    )
    head = pooled_features if return_pooled else pooled_logits
    if packed:
        # per-segment pooled-output gather: [B, S, H] at [B, M] offsets
        pos = batch["cls_positions"].astype(jnp.int32)
        if seq_axis is not None:
            # cls offsets are GLOBAL; hidden is this shard's [B, S_local]
            # slice.  Each shard gathers the offsets landing in its slice
            # (clipped gather, masked) and a psum assembles the full
            # [B, M, H] on every shard — the packed analog of the
            # shard-0 [CLS] broadcast below, same head-grads-counted-once
            # contract (the sp loss is gated to seq-shard 0).
            S_local = hidden.shape[1]
            off = jax.lax.axis_index(seq_axis) * S_local
            local = pos - off
            inb = (local >= 0) & (local < S_local)
            safe = jnp.clip(local, 0, S_local - 1)
            hM = jnp.take_along_axis(hidden, safe[..., None], axis=1)
            hM = jax.lax.psum(
                hM * inb[..., None].astype(hidden.dtype), seq_axis)
        else:
            hM = jnp.take_along_axis(hidden, pos[..., None], axis=1)
        B, M, H = hM.shape
        out = head(params, cfg, hM.reshape(B * M, H), dtype=dtype,
                   drop_rng=None if deterministic else drop_rng)
        out = out.reshape(B, M, -1)
        return (out, aux) if return_aux else out
    h0 = hidden[:, 0, :]
    if seq_axis is not None:
        on_shard0 = (jax.lax.axis_index(seq_axis) == 0).astype(h0.dtype)
        h0 = jax.lax.psum(h0 * on_shard0, seq_axis)
    out = head(params, cfg, h0, dtype=dtype,
               drop_rng=None if deterministic else drop_rng)
    return (out, aux) if return_aux else out


def pooled_features(params: Params, cfg: BertConfig, h0: jax.Array, *,
                    dtype=jnp.float32, drop_rng=None) -> jax.Array:
    """[CLS] hidden rows [B, H] -> pooled pre-classifier features [B, H]
    (tanh pooler + optional dropout) — the classifier's input, split out so
    the fused projection+CE kernel (``ops.fused_ce``) can consume the final
    matmul itself."""
    pooled = jnp.tanh(_dense(h0, params["pooler"], dtype))
    if drop_rng is not None:
        pooled = _dropout(pooled, cfg.dropout, drop_rng)
    return pooled


def pooled_logits(params: Params, cfg: BertConfig, h0: jax.Array, *,
                  dtype=jnp.float32, drop_rng=None) -> jax.Array:
    """[CLS] hidden rows [B, H] -> logits [B, num_labels] (fp32): tanh
    pooler, optional dropout (``drop_rng`` given), classifier.  Shared by
    ``classify`` and the pipeline-parallel path so the head cannot drift
    between them."""
    pooled = pooled_features(params, cfg, h0, dtype=dtype, drop_rng=drop_rng)
    logits = _dense(pooled, params["classifier"], dtype)
    return logits.astype(jnp.float32)
