"""Model zoo: functional BERT encoder + classification head.

``get_config(name)`` resolves an architecture; ``bert.init_params`` /
``bert.classify`` are the init/apply pair every trainer and entrypoint uses.
"""
from pdnlp_tpu.models.config import BertConfig, available_models, get_config
from pdnlp_tpu.models import bert
from pdnlp_tpu.models import decoder

__all__ = ["BertConfig", "available_models", "get_config", "bert",
           "decoder"]
