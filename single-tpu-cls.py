"""Single-device training — strategy 1 of the capability matrix.

Capability twin of ``/root/reference/single-gpu-cls.py``: one device, batch
32, seq len 128, 1 epoch over the seeded 9,200-example split (288 steps),
AdamW 3e-5, per-step ``【train】`` lines, ``耗时：X分钟`` wall-clock, final
checkpoint, then a test pass with a per-class report.

TPU-native shape: the whole step is one jitted XLA program on the chip; the
loader prefetches/collates on the host thread while the device runs.

    python single-tpu-cls.py [--dtype bfloat16] [--dev true] ...
"""
import jax

from pdnlp_tpu.data.corpus import LABELS
from pdnlp_tpu.train import Trainer, make_eval_step, make_train_step, setup_data, setup_model
from pdnlp_tpu.train.steps import make_multi_step
from pdnlp_tpu.utils.config import Args, parse_cli
from pdnlp_tpu.utils.logging import rank0_print
from pdnlp_tpu.utils.metrics import classification_report


def main(args: Args) -> float:
    from pdnlp_tpu.train.setup import setup_pipeline

    train_loader, dev_loader, tok = setup_data(args)
    cfg, tx, state = setup_model(args, tok.vocab_size,
                                 total_steps=len(train_loader) * args.epochs)
    # device-resident input (default): the encoded split lives on the chip,
    # steady-state steps pay zero host->device transport (data/pipeline.py)
    pipeline = setup_pipeline(args, train_loader)
    rank0_print(f"device: {jax.devices()[0].platform}  model: {args.model}  "
                f"dtype: {args.dtype}  steps/epoch: {len(train_loader)}  "
                f"pipeline: {pipeline.mode}")
    trainer = Trainer(
        args, cfg, state,
        make_train_step(cfg, tx, args), make_eval_step(cfg, args),
        multi_step=make_multi_step(cfg, tx, args) if args.fuse_steps > 1 else None,
        pipeline=pipeline)
    minutes = trainer.train(train_loader, dev_loader)
    # dev set doubles as the test set (single-gpu-cls.py:241-247)
    result = trainer.test(dev_loader)
    rank0_print(f"test loss：{result['loss']:.6f} accuracy：{result['accuracy']:.4f}")
    rank0_print(classification_report(result["y_true"], result["y_pred"], LABELS))
    return minutes


if __name__ == "__main__":
    main(parse_cli(base=Args(strategy="single")))
