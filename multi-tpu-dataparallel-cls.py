"""Single-process batch-scatter training — the ``nn.DataParallel`` analog.

Capability twin of ``/root/reference/multi-gpu-dataparallel-cls.py:255``:
one controller process, the SAME 32-row global batch as single-device,
scattered across chips each step (so the step count stays 288 — the
reference's DataParallel does not shrink steps, ``README.md:44-74``).
On TPU this is the same jitted program as DP with a smaller per-device
batch; the scatter/gather the reference does per step is just the batch's
sharding.  Expect it to beat single-device but lose to ``multi-tpu-jax-cls``
— same relative ordering as the reference's table (2.03 vs 1.41 min).

    python multi-tpu-dataparallel-cls.py
"""
from pdnlp_tpu.train.run import run_parallel
from pdnlp_tpu.utils.config import Args, parse_cli

if __name__ == "__main__":
    run_parallel(parse_cli(base=Args(strategy="dataparallel")),
                 mode="dp", scale_batch=False)
