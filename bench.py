#!/usr/bin/env python
"""Benchmark: north-star config (mesh DP + bf16) on the real corpus.

Prints ONE machine-parseable JSON line:
    {"metric": ..., "value": N, "unit": "min", "vs_baseline": N, ...}

``value`` is wall-clock minutes for one full training epoch (288 steps at
batch 32 on one chip; steps shrink as the data axis widens), the reference's
own headline metric (``耗时：X分钟``, ``/root/reference/README.md:10-20``).
``vs_baseline`` is the speedup against the published north-star wall-clock —
2-GPU DDP+AMP, 0.6336 min (``README.md:16``) — so > 1.0 beats it.

Methodology notes (vs the reference's timing):
- the timed epoch starts AFTER the train step is compiled (AOT ``.lower()
  .compile()``), the analog of the reference's warm CUDA context; XLA's
  persistent compilation cache under ``output/`` makes reruns cheap;
- dev accuracy is measured after the timer stops, like the reference's
  separate ``test()`` pass;
- training logs go to stderr; stdout carries only the JSON line.
"""
from __future__ import annotations

import contextlib
import json
import sys

NORTH_STAR_MIN = 0.6336       # 2-GPU DDP+AMP, README.md:16
SINGLE_GPU_MIN = 2.8276       # 1-GPU fp32, README.md:12


def main() -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", "output/xla_cache")

    from pdnlp_tpu.train.run import build_parallel_trainer
    from pdnlp_tpu.utils.config import Args, parse_cli

    # fuse_steps stays 1: K-step scan fusion is math-identical but measured
    # SLOWER on this shape (0.37 vs 0.23 min at K=8 — scan-carried weights
    # lose XLA layout/fusion freedom); it remains a CLI knob for
    # dispatch-bound deployments.
    args = parse_cli(base=Args(
        strategy="dp", dtype="bfloat16",
        dev=True,            # suppress the end-of-run checkpoint write
        log_every=10 ** 9,   # no per-step printing inside the timed loop
    ))

    with contextlib.redirect_stdout(sys.stderr):
        import numpy as np

        trainer, train_loader, dev_loader = build_parallel_trainer(args, mode="dp")
        # compile outside the timer (the reference times a warm CUDA context)
        host_batch = next(iter(train_loader))
        batch = trainer.put(host_batch)
        trainer.train_step.lower(trainer.state, batch).compile()
        trainer.eval_step.lower(trainer.state["params"], batch).compile()
        if trainer.multi_step is not None:
            stacked = {k: np.stack([v] * args.fuse_steps)
                       for k, v in host_batch.items()}
            trainer.multi_step.lower(
                trainer.state, trainer.put_fused(stacked)).compile()
        minutes = trainer.train(train_loader, dev_loader=None)
        loss, acc = trainer.dev(dev_loader)

    print(json.dumps({
        "metric": "wall_clock_min_per_epoch",
        "value": round(minutes, 4),
        "unit": "min",
        "vs_baseline": round(NORTH_STAR_MIN / minutes, 4),
        "baseline_min": NORTH_STAR_MIN,
        "single_gpu_baseline_min": SINGLE_GPU_MIN,
        "dev_accuracy": round(acc, 4),
        "dev_loss": round(loss, 4),
        "steps_per_epoch": len(train_loader),
        "devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
        "dtype": args.dtype,
        "fuse_steps": args.fuse_steps,
        "note": "from-scratch weights (no pretrained ckpt in image); "
                "reference dev acc 0.57 is from a pretrained model",
    }))


if __name__ == "__main__":
    main()
