#!/usr/bin/env python
"""Benchmark: north-star config (mesh DP + bf16) on the real corpus.

Prints ONE machine-parseable JSON line:
    {"metric": ..., "value": N, "unit": "min", "vs_baseline": N, ...}

``value`` is the TOTAL training wall-clock in minutes — every epoch of the
shipped recipe, the number a user actually waits for — with ``min_per_epoch``
and ``minutes_to_target`` (first in-loop eval >= the reference's 0.57
accuracy) alongside.  The reference's headline is its own total wall-clock
(one epoch, ``耗时：X分钟``, ``/root/reference/README.md:10-20``);
``vs_baseline`` is the speedup of this TOTAL against the published
north-star — 2-GPU DDP+AMP, 0.6336 min (``README.md:16``) — so > 1.0 beats
it outright, not per-epoch.

Accuracy: the reference fine-tunes *pretrained* ``hfl/chinese-bert-wwm-ext``
(dev acc ~0.57).  This environment has no egress, so the warm start is
produced in-repo: ``pretrain-tpu.py`` (masked-LM over the 40k-text corpus,
fine-tune dev split held out).  The bench fine-tunes from
``output/pretrained-tanh.msgpack`` (the cache name carries the activation;
``--gelu erf`` uses ``pretrained.msgpack``), regenerating it first if
absent (~20 min, one-time; reruns hit the cached file).  The pretrain stage is NOT part of
the timed epoch — the reference's download of model_hub weights isn't timed
either.

Scope: the bench is a SINGLE-HOST harness (the pretrain-cache check is a
local-filesystem gate; multi-host runs should pretrain explicitly first),
and ``mfu_pct`` assumes the default pure-DP mesh — under ``--mesh_shape``
with tp/sp axes the per-chip FLOP share changes and the field is not
comparable.

Flag note: ``--pipeline <mode|all>`` is the input-pipeline COMPARISON smoke
(``pipeline_smoke`` below, per-mode steps/s + transport counters), not a
knob of the headline bench — it intercepts before ``Args`` parsing.  The
headline bench always runs ``Args.pipeline="auto"`` (device-resident when
eligible; that IS the shipped optimization) and reports the resolved mode
plus measured transport in its JSON (``pipeline``/``transport``).  Other
entrypoints (``single-tpu-cls.py``, ``multi-tpu-*-cls.py``) expose
``--pipeline`` as the ordinary mode override.

Methodology notes (vs the reference's timing):
- the timed epoch starts AFTER the train step is compiled (AOT ``.lower()
  .compile()``), the analog of the reference's warm CUDA context; XLA's
  persistent compilation cache under ``output/`` makes reruns cheap;
- dev accuracy is measured after the timer stops, like the reference's
  separate ``test()`` pass;
- training logs go to stderr; stdout carries only the JSON line.
"""
from __future__ import annotations

import contextlib
import json
import os
import sys

NORTH_STAR_MIN = 0.6336       # 2-GPU DDP+AMP, README.md:16
SINGLE_GPU_MIN = 2.8276       # 1-GPU fp32, README.md:12
# per-chip bf16 peak FLOP/s by device kind (prefix-matched); MFU is only
# reported when the running chip is recognized
BF16_PEAK_BY_KIND = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,    # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,    # v6e / Trillium
    "TPU v6e": 918e12,
}


def bf16_peak(device) -> float | None:
    kind = getattr(device, "device_kind", "")
    for prefix, peak in BF16_PEAK_BY_KIND.items():
        if kind.startswith(prefix):
            return peak
    return None


def step_flops(cfg, batch: int, seq: int) -> float:
    """Matmul FLOPs of one fused train step (fwd + 2x bwd), excluding
    embedding gathers: 6 * (encoder matmul params) * tokens + attention."""
    H, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    mm_params = L * (4 * H * H + 2 * H * I) + H * H  # qkvo + mlp + pooler
    tokens = batch * seq
    dense = 6 * mm_params * tokens
    attn = L * 3 * 2 * 2 * batch * cfg.num_heads * seq * seq * cfg.head_dim
    return dense + attn


def serve_smoke(argv) -> None:
    """``--serve``: inference-serving smoke over the offline path.

    N mixed-length requests spanning >= 3 sequence buckets, driven through
    ``pdnlp_tpu.serve`` after a bucket warmup.  Reports req/s, latency
    p50/p99, batch occupancy, compile-cache hit/miss and — the acceptance
    bar — the retrace count AFTER warmup, which must be zero: steady-state
    serving never re-traces.  Writes the snapshot to ``results/
    serve_smoke.json`` (override: ``--serve_out``); request count:
    ``--serve_requests`` (default 120).  Deterministic and CPU-safe: texts
    are synthesized from a seeded RNG (over the corpus vocab when present,
    a fixed CJK set otherwise), so the smoke needs no dataset or
    checkpoint — though a checkpoint under ``--output_dir`` is used when
    one exists.
    """
    import random
    import time

    import jax

    from pdnlp_tpu.data.tokenizer import WordPieceTokenizer, build_vocab
    from pdnlp_tpu.parallel import make_mesh
    from pdnlp_tpu.serve import InferenceEngine
    from pdnlp_tpu.serve.offline import score_texts
    from pdnlp_tpu.utils.config import Args, parse_cli, pop_cli_flag

    argv, n_requests = pop_cli_flag(argv, "--serve_requests", 120, int)
    argv, out_path = pop_cli_flag(
        argv, "--serve_out", os.path.join("results", "serve_smoke.json"))
    args = parse_cli(argv, base=Args())

    # deterministic mixed-length traffic: char counts sized so token lengths
    # (chars + [CLS]/[SEP]) land in the 32/64/128 buckets
    chars = "天地人你我他好坏大小上下来去爱恨喜怒哀乐高兴悲伤讨厌愤怒"
    rng = random.Random(args.seed)
    lengths = [10, 24, 48, 60, 100, 120]
    texts = ["".join(rng.choice(chars) for _ in range(lengths[i % len(lengths)]))
             for i in range(n_requests)]

    if os.path.exists(args.data_path) or os.path.exists(args.vocab_path):
        from pdnlp_tpu.data.tokenizer import get_or_build_vocab

        tok = WordPieceTokenizer(get_or_build_vocab(args))
    else:
        # no corpus on this host: a vocab over the synthetic char set keeps
        # the smoke self-contained (latency/retrace numbers don't care)
        tok = WordPieceTokenizer(build_vocab(texts, size=256))

    buckets = (32, 64, 128)
    batch_size = 8
    mesh = make_mesh(num_devices=args.num_devices, shape=args.mesh_shape)
    engine = InferenceEngine(args, tokenizer=tok, mesh=mesh)
    from pdnlp_tpu.train import checkpoint as ckpt_mod

    ckpt_path = ckpt_mod.latest(args.output_dir)
    if ckpt_path:
        try:
            engine.load_checkpoint(ckpt_path)
        except Exception as e:
            print(f"checkpoint {ckpt_path} not loadable ({e}); "
                  "serving init weights", file=sys.stderr)

    engine.warmup(buckets, engine.pad_rows(batch_size))
    retraces_warmup = engine.metrics.retraces.value

    t0 = time.monotonic()
    preds, _ = score_texts(engine, texts, buckets=buckets,
                           batch_size=batch_size)
    elapsed = time.monotonic() - t0

    snap = engine.metrics.snapshot()
    retraces_post = engine.metrics.retraces.value - retraces_warmup
    result = {
        "metric": "serve_smoke",
        "requests": n_requests,
        "req_per_sec": round(n_requests / elapsed, 2),
        "elapsed_sec": round(elapsed, 3),
        "latency_ms_p50": snap["request_latency_ms"]["p50"],
        "latency_ms_p99": snap["request_latency_ms"]["p99"],
        "batch_occupancy_mean": snap["batch_occupancy"]["mean"],
        "buckets": list(buckets),
        "batch_size": batch_size,
        "retraces_warmup": retraces_warmup,
        "retraces_post_warmup": retraces_post,
        "cache_hits": snap["compile_cache"]["hits"],
        "cache_misses": snap["compile_cache"]["misses"],
        "checkpoint": engine.checkpoint_path,
        "model": args.model,
        "dtype": args.dtype,
        # what the engine actually serves: the forward precision label
        # ("int8" under --serve_dtype int8) and the routed attention impl
        # (headline at max_seq_len; per-bucket routing alongside — sub-128
        # buckets fall back to XLA under a pallas request)
        "serve_dtype": engine.dtype_label,
        "attn_impl": engine.attn_impl,
        "attn_impl_by_seq": {str(s): i for s, i
                             in sorted(engine.attn_impl_by_seq.items())},
        "devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
        "metrics": snap,
    }
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, out_path)
    print(json.dumps({k: v for k, v in result.items() if k != "metrics"}))
    if retraces_post != 0:
        # the smoke's whole point: steady-state serving never re-traces.
        # A nonzero count here is a shape-stability regression (dtype/
        # weak-type drift, bucket plumbing) — fail loudly, snapshot kept.
        sys.exit(f"serve smoke FAILED: {retraces_post} post-warmup retraces "
                 f"(expected 0) — see {out_path}")


def decode_smoke(argv) -> None:
    """``--decode``: the generative-decoding gate (ROADMAP item 1).

    A closed-loop storm of ``--decode_streams`` mixed-length prompts
    through the continuous-batching decode engine
    (``pdnlp_tpu.serve.decode``), gating the properties the KV cache
    exists to buy:

    - **tokens/s/chip >= 2x a no-cache re-prefill baseline** — the same
      prompts generating the same token counts by re-running the bucketed
      causal prefill per token (the cost of generation WITHOUT a cache,
      batched just as wide, on the same engine programs);
    - **zero post-warmup retraces** across the prefill buckets AND the
      one fixed ``[slots, 1]`` decode shape;
    - **inter-token p99 under ``--decode_p99_ms``** with continuous
      batching holding **mean slot occupancy >= 0.8** under the mixed
      stream mix;
    - **chain integrity through a mid-storm replica kill**: a 2-replica
      router storm, replica 0 killed once demonstrably mid-decode; every
      stream's hop chain must validate through the trace-file round trip
      AND every stream must emit EXACTLY the single-engine reference
      token sequence (orphans re-prefill on the survivor — no duplicated,
      no lost tokens);
    - **paged shared-prefix storm** (phase D, the paged-KV gate): an
      80%-shared prompt mix at EQUAL ``--kv_hbm_mb`` must seat >= 3x the
      slot layout's concurrent streams (peak live), every stream
      token-identical to the slot-cache baseline, a prefix-hit resubmit
      must run ZERO prefill forwards (TTFT bounded by one decode-step
      latency, by construction: the stored first token is emitted at
      claim), zero post-warmup retraces on the paged path, and the page
      allocator's ledger must reconcile to ZERO leaked pages after drain
      — including through a 2-replica paged kill storm whose re-prefilled
      survivors re-attach to shared prefix pages;
    - **speculative decoding** (phase E, ROADMAP item 3): draft-k /
      verify-1 over a paged primary/drafter pair must deliver >= 1.8x
      tokens/s vs primary-only decode at BITWISE token parity per
      stream, zero post-warmup retraces on both engines, zero leaked
      pages after drain (including through a mid-storm drafter kill
      that degrades the pair to primary-only at exact-token parity),
      complete draft -> verify hop chains through the trace-file round
      trip, and a ``ServeController`` that demonstrably adapts k on an
      injected low-acceptance stream — halve, disable, and auto-revert
      a regressing re-enable — with every actuation's decision chain
      complete.  The drafter/primary COST RATIO is the one emulated
      quantity (untrained weights can't give a genuinely cheap model a
      real acceptance rate), calibrated per host: every primary
      dispatch is padded to the MEASURED per-step cost of a real
      bert-small engine while the drafter runs bert-tiny at full speed.
    - **disaggregated pools** (phase F, ROADMAP item 4): the same mixed
      storm through an interleaved single-engine batcher and through a
      3-engine prefill/decode pool split (socket transport), with every
      prefill dispatch padded by a fixed cost on BOTH setups.  Gates:
      the interleaved inter-token p99 must inherit the prefill cost
      while the decode pool's p99 stays under it (the isolation claim),
      bitwise token parity between the two setups, zero post-warmup
      retraces across all four engines, complete hop chains with every
      stream crossing the pool boundary exactly through a ``handoff``
      hop, zero wire-frame errors, and — through a mid-storm decode-
      replica kill — requeued orphans that re-home through the front
      door at exact-token parity with reconciled survivor page ledgers.

    Deterministic and CPU-safe (seeded prompts over a synthetic vocab,
    greedy decode, EOS disabled so token counts are exact); snapshot at
    ``results/decode_smoke.json``, non-zero exit on any violation.
    """
    import tempfile
    import time

    import jax
    import numpy as np

    from pdnlp_tpu.data.tokenizer import WordPieceTokenizer, build_vocab
    from pdnlp_tpu.obs.decision import validate_decisions
    from pdnlp_tpu.obs.request import validate_chains
    from pdnlp_tpu.serve import (
        DecodeBatcher, DecodeEngine, DecodeRouter, PagedDecodeEngine,
        ServeController,
    )
    from pdnlp_tpu.serve.decode import DisaggDecodeRouter
    from pdnlp_tpu.utils.config import Args, parse_cli, pop_cli_flag

    argv, n_streams = pop_cli_flag(argv, "--decode_streams", 48, int)
    argv, slots = pop_cli_flag(argv, "--decode_slots_n", 8, int)
    argv, max_new = pop_cli_flag(argv, "--decode_max_new", 24, int)
    argv, p99_budget = pop_cli_flag(argv, "--decode_p99_ms", 500.0, float)
    argv, out_path = pop_cli_flag(
        argv, "--decode_out", os.path.join("results", "decode_smoke.json"))
    # jaxlint: disable=L1 — smoke artifact dir, kept for post-run triage
    trace_dir = tempfile.mkdtemp(prefix="decode_smoke_trace_")
    args = parse_cli(argv, base=Args(
        model="bert-tiny", decode_slots=slots, decode_max_len=96,
        max_new_tokens=max_new, trace=True, trace_dir=trace_dir))
    buckets = (16, 32, 64)

    chars = "天地人你我他好坏大小上下来去爱恨喜怒哀乐高兴悲伤讨厌愤怒"
    tok = WordPieceTokenizer(build_vocab([chars], size=256))
    rng = np.random.default_rng(args.seed)
    lens = rng.integers(3, 40, n_streams)
    prompts = [rng.integers(5, tok.vocab_size, int(k)).tolist()
               for k in lens]
    failures = []

    def make_engine():
        return DecodeEngine(args, tokenizer=tok, mesh=None,
                            buckets=buckets)

    # ---------------------------------------------- phase A: cached decode
    engine = make_engine()
    batcher = DecodeBatcher(engine, max_waiting=n_streams).start()
    batcher.eos_id = -1  # deterministic token counts
    batcher.warmup()
    retr0 = engine.metrics.retraces.value
    miss0 = engine.metrics.cache_misses.value
    t0 = time.monotonic()
    streams = [batcher.submit_ids(p, max_new_tokens=max_new)
               for p in prompts]
    refs = [s.result(timeout=600) for s in streams]
    decode_sec = time.monotonic() - t0
    snap = batcher.snapshot()
    batcher.stop()
    tokens_out = snap["decode"]["tokens_out_total"]
    retraces_post = engine.metrics.retraces.value - retr0
    misses_post = engine.metrics.cache_misses.value - miss0
    n_chips = jax.device_count()
    decode_tps_chip = tokens_out / decode_sec / n_chips
    occupancy_mean = snap["replica"]["slot_occupancy"]["mean"]
    intertoken_p99 = snap["decode"]["intertoken_ms"]["p99"]

    # ------------------------------------- phase B: no-cache re-prefill
    # the same generations WITHOUT a KV cache: every token re-runs the
    # bucketed causal prefill over prompt + generated-so-far, batched
    # prefill_rows wide on the same engine programs (filler slot ids, so
    # nothing touches the cache) — the honest cost of cacheless decoding
    rows = engine.prefill_rows
    t0 = time.monotonic()
    base_tokens = 0
    for i in range(0, n_streams, rows):
        group = list(range(i, min(i + rows, n_streams)))
        seqs = [list(prompts[g]) for g in group]
        done = [False] * len(group)
        while not all(done):
            live = [j for j in range(len(group)) if not done[j]]
            logits = engine.prefill_ids(
                [seqs[j] for j in live],
                [engine.slots] * len(live))  # OOB: cache untouched
            for r, j in enumerate(live):
                seqs[j].append(int(np.argmax(logits[r])))
                base_tokens += 1
                g = group[j]
                if len(seqs[j]) - len(prompts[g]) >= len(refs[g]):
                    done[j] = True
    baseline_sec = time.monotonic() - t0
    baseline_tps_chip = base_tokens / baseline_sec / n_chips
    speedup = decode_tps_chip / baseline_tps_chip

    # the baseline must reproduce the cached path's tokens — otherwise
    # the speedup compares garbage.  One seeded stream re-verified here
    # (the full bitwise contract is tier-1's test_decode job)
    parity_ok = True
    g0 = list(prompts[0])
    for t in refs[0]:
        lg = engine.prefill_ids([g0], [engine.slots])
        if int(np.argmax(lg[0])) != t:
            parity_ok = False
            break
        g0.append(t)

    # ------------------------------------------- phase C: replica kill
    engines = [make_engine() for _ in range(2)]
    tracer = engines[0].tracer
    for e in engines[1:]:
        e.tracer = tracer
    router = DecodeRouter(engines, max_waiting=n_streams).start()
    for b in router.batchers:
        b.eos_id = -1
    router.warmup()
    kill_retr0 = sum(e.metrics.retraces.value for e in engines)
    kstreams = [router.submit_ids(p, max_new_tokens=max_new)
                for p in prompts]
    deadline = time.monotonic() + 120
    while (router.batchers[0].metrics.tokens_out_total.value
           < max_new * slots and time.monotonic() < deadline):
        time.sleep(0.002)
    router.kill(0)
    kouts = [s.result(timeout=600) for s in kstreams]
    kill_retraces = sum(e.metrics.retraces.value
                        for e in engines) - kill_retr0
    requeued_in = router.batchers[1].rmetrics.requeued_in.value
    router.stop()
    kill_parity = kouts == refs

    # chain integrity through the FILE round trip: flush, re-read, check
    trace_path = tracer.flush()
    records = []
    with open(trace_path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    report = validate_chains(records, [s.rid for s in kstreams])

    # ----------------------------- phase D: paged shared-prefix storm
    # The paged-KV capacity claim, head to head at EQUAL --kv_hbm_mb: a
    # budget worth FOUR max-length slot stripes, an 80%-shared prompt
    # mix (one 32-token system prefix + short distinct suffixes; every
    # 5th prompt unique), and the same storm driven through (a) the slot
    # layout — capped to 4 slots — and (b) the paged layout, whose
    # shared streams pin the prefix's 2 pages once and reserve ~1
    # private page each.  Gates: >= 3x peak concurrent live streams,
    # token parity stream for stream, a structurally-zero-prefill
    # full-hit resubmit, zero post-warmup retraces, and a reconciled
    # (zero-leak) page ledger after drain — then once more through a
    # 2-replica paged kill storm.
    pd_slots, pd_page_sz, pd_max_len, pd_max_new = 16, 16, 96, 8
    probe_eng = PagedDecodeEngine(
        parse_cli([], base=Args(model="bert-tiny", decode_slots=1,
                                decode_max_len=pd_max_len,
                                kv_page_sz=pd_page_sz)),
        tokenizer=tok, mesh=None, buckets=buckets)
    budget_mb = 4 * probe_eng.token_bytes * pd_max_len / 2**20
    del probe_eng

    def pd_args():
        return parse_cli([], base=Args(
            model="bert-tiny", decode_slots=pd_slots,
            decode_max_len=pd_max_len, max_new_tokens=pd_max_new,
            kv_page_sz=pd_page_sz, kv_hbm_mb=budget_mb,
            seed=args.seed))

    n_shared_storm = 60
    shared_prefix = rng.integers(5, tok.vocab_size, 32).tolist()
    # one warm stream carries the shared prefix through a full prefill
    # BEFORE the storm (the realistic shape: the prefix is indexed from
    # earlier traffic) — without it the opening claim burst is all-cold
    # and the concurrency comparison measures nothing but the cold pool
    warm_prompt = shared_prefix + rng.integers(5, tok.vocab_size,
                                               4).tolist()
    storm_prompts = []
    for i in range(n_shared_storm):
        if i % 5 == 4:      # 20%: unique, same total length
            storm_prompts.append(
                rng.integers(5, tok.vocab_size, 36).tolist())
        else:               # 80%: shared 32-token prefix, distinct tail
            storm_prompts.append(
                shared_prefix + rng.integers(5, tok.vocab_size,
                                             4).tolist())

    def pd_storm(engine):
        b = DecodeBatcher(engine, max_waiting=n_shared_storm).start()
        b.eos_id = -1
        b.warmup()
        r0 = engine.metrics.retraces.value
        m0 = engine.metrics.cache_misses.value
        # identical warm stream on BOTH layouts (the slot engine just
        # runs one extra stream, the paged engine also indexes the
        # shared prefix) so the storms stay apples-to-apples
        b.submit_ids(warm_prompt,
                     max_new_tokens=pd_max_new).result(timeout=600)
        ss = [b.submit_ids(p, max_new_tokens=pd_max_new)
              for p in storm_prompts]
        outs = [s.result(timeout=600) for s in ss]
        return b, outs, r0, m0

    slot_b, slot_outs, _, _ = pd_storm(
        DecodeEngine(pd_args(), tokenizer=tok, mesh=None,
                     buckets=buckets))
    slot_peak = slot_b.metrics.peak_live_streams.value
    slot_cap = slot_b.engine.slots
    slot_b.stop()

    paged_eng = PagedDecodeEngine(pd_args(), tokenizer=tok, mesh=None,
                                  buckets=buckets)
    paged_b, paged_outs, pd_r0, pd_m0 = pd_storm(paged_eng)
    paged_peak = paged_b.metrics.peak_live_streams.value
    # full-hit probe: prime the index with one post-drain submission
    # (registers the prompt — its storm-time entry may have been under
    # eviction pressure), then an exact repeat must emit its first token
    # WITHOUT a prefill forward (TTFT is then bounded by one decode-step
    # wait, by construction)
    paged_b.submit_ids(storm_prompts[0],
                       max_new_tokens=pd_max_new).result(timeout=600)
    pre0 = paged_b.metrics.prefills_total.value
    hs = paged_b.submit_ids(storm_prompts[0], max_new_tokens=pd_max_new)
    hit_out = hs.result(timeout=600)
    hit_prefills = paged_b.metrics.prefills_total.value - pre0
    hit_ttft_ms = (hs.first_token_at - hs.born) * 1e3
    pd_retraces = paged_eng.metrics.retraces.value - pd_r0
    pd_misses = paged_eng.metrics.cache_misses.value - pd_m0
    paged_snap = paged_b.snapshot()
    paged_b.stop()
    leak = paged_eng.leak_check()
    paged_eng.prefix.clear()
    drained_clean = (leak["ok"] and not leak["stream_owners"]
                     and paged_eng.allocator.free_pages
                     == paged_eng.n_pages)
    pd_parity = (paged_outs == slot_outs
                 and hit_out == slot_outs[0])

    # 2-replica paged kill: orphans re-prefill on the survivor,
    # re-attaching to ITS shared prefix pages under the same request id
    pengines = [PagedDecodeEngine(pd_args(), tokenizer=tok, mesh=None,
                                  buckets=buckets) for _ in range(2)]
    for e in pengines[1:]:
        e.tracer = pengines[0].tracer
    prouter = DecodeRouter(pengines,
                           max_waiting=n_shared_storm).start()
    for b in prouter.batchers:
        b.eos_id = -1
    prouter.warmup()
    pkstreams = [prouter.submit_ids(p, max_new_tokens=pd_max_new)
                 for p in storm_prompts]
    deadline = time.monotonic() + 120
    while (prouter.batchers[0].metrics.tokens_out_total.value
           < pd_max_new * 4 and time.monotonic() < deadline):
        time.sleep(0.002)
    prouter.kill(0)
    pkouts = [s.result(timeout=600) for s in pkstreams]
    pk_requeued = prouter.batchers[1].rmetrics.requeued_in.value
    prouter.stop()
    survivor = prouter.batchers[1].engine
    pk_leak = survivor.leak_check()
    pk_hits = survivor.prefix.snapshot()
    survivor.prefix.clear()
    pk_clean = (pk_leak["ok"] and not pk_leak["stream_owners"]
                and survivor.allocator.free_pages == survivor.n_pages)
    pk_parity = pkouts == slot_outs

    # ------------------------------ phase E: speculative decoding
    # Draft-k / verify-1 (ROADMAP item 3): the cheap model drafts k
    # tokens through its own paged cache, the primary scores all k+1
    # positions in ONE fixed-shape verify call, and the longest accepted
    # greedy prefix commits to both caches — bitwise identical to
    # primary-only decode by construction.  Everything measured here is
    # REAL machinery — draft rounds, the [slots, k+1] verify program,
    # two-owner page custody, acceptance, retrace/leak ledgers, the
    # drafter-death degrade, the controller's k law — except the COST
    # RATIO between the two models: with untrained weights a genuinely
    # cheap model never agrees with a different random model, and an
    # equal-cost drafter has nothing to amortize.  So the pair runs
    # identical-seed bert-tiny weights (the acceptance ceiling) while
    # every primary dispatch is padded to the MEASURED per-step cost of
    # a real bert-small engine on this host.  The >= 1.8x gate is then
    # the round algebra — (k+1) tokens for k cheap drafts plus one
    # primary-priced verify — surviving the implementation's real
    # bookkeeping overhead at an honest, host-calibrated ratio.
    spec_k = 6

    def step_cost_s(model):
        # median warmed [slots, 1] decode-step wall time (all-dead rows:
        # sentinel tables, no live page touched — compute is identical)
        e = PagedDecodeEngine(
            parse_cli([], base=Args(model=model, decode_slots=pd_slots,
                                    decode_max_len=pd_max_len,
                                    kv_page_sz=pd_page_sz)),
            tokenizer=tok, mesh=None, buckets=buckets)
        e.warmup_decode()
        tk = np.zeros((pd_slots,), np.int32)
        ps = np.zeros((pd_slots,), np.int32)
        samples = []
        for _ in range(30):
            t0 = time.perf_counter()
            np.asarray(e.decode_batch(tk, ps, live=0))
            samples.append(time.perf_counter() - t0)
        return float(np.median(samples))

    tiny_step_s = step_cost_s("bert-tiny")
    small_step_s = step_cost_s("bert-small")

    def pad_primary(engine):
        # applied AFTER warmup: compile time stays unpadded and the
        # retrace/cache-miss ledgers are untouched — only dispatch wall
        # time moves, up to the measured bert-small step cost
        for name in ("decode_batch", "verify_ids", "prefill_ids"):
            orig = getattr(engine, name)

            def padded(*a, _orig=orig, **kw):
                t0 = time.perf_counter()
                out = np.asarray(_orig(*a, **kw))
                lack = small_step_s - (time.perf_counter() - t0)
                if lack > 0:
                    time.sleep(lack)
                return out
            setattr(engine, name, padded)

    sargs = parse_cli([], base=Args(
        model="bert-tiny", decode_slots=pd_slots,
        decode_max_len=pd_max_len, max_new_tokens=max_new,
        kv_page_sz=pd_page_sz, seed=args.seed, trace=True,
        trace_dir=trace_dir))
    spec_trace = []   # the phase-local tracer, shared by every engine

    def spec_engine(prefix_share=True):
        e = PagedDecodeEngine(
            sargs, tokenizer=tok, mesh=None, buckets=buckets,
            tracer=(spec_trace[0] if spec_trace else None),
            prefix_share=prefix_share)
        if not spec_trace:
            spec_trace.append(e.tracer)
        return e

    # E1 — primary-only reference: same engine class, same prompts,
    # same padded primary cost, no drafter.  Its outputs are the
    # bitwise-parity reference AND the tokens/s denominator.
    ref_eng = spec_engine()
    ref_b = DecodeBatcher(ref_eng, max_waiting=n_streams).start()
    ref_b.eos_id = -1
    ref_b.warmup()
    pad_primary(ref_eng)
    t0 = time.monotonic()
    ref_streams = [ref_b.submit_ids(p, max_new_tokens=max_new)
                   for p in prompts]
    sp_refs = [s.result(timeout=600) for s in ref_streams]
    sp_base_sec = time.monotonic() - t0
    ref_b.stop()
    sp_base_tps = sum(len(o) for o in sp_refs) / sp_base_sec

    # E2 — the speculative pair through a 1-replica DecodeRouter (the
    # fleet wiring: paired drafter, draft_k knob, control surface)
    sp_eng = spec_engine()
    sp_dr = spec_engine(prefix_share=False)
    srouter = DecodeRouter([sp_eng], drafters=[sp_dr], draft_k=spec_k,
                           max_waiting=n_streams).start()
    sb = srouter.batchers[0]
    sb.eos_id = -1
    srouter.warmup()
    sp_r0 = sp_eng.metrics.retraces.value + sp_dr.metrics.retraces.value
    sp_m0 = (sp_eng.metrics.cache_misses.value
             + sp_dr.metrics.cache_misses.value)
    pad_primary(sp_eng)
    t0 = time.monotonic()
    sstreams = [srouter.submit_ids(p, max_new_tokens=max_new)
                for p in prompts]
    sp_outs = [s.result(timeout=600) for s in sstreams]
    sp_sec = time.monotonic() - t0
    sp_tps = sum(len(o) for o in sp_outs) / sp_sec
    sp_speedup = sp_tps / sp_base_tps
    sp_retraces = (sp_eng.metrics.retraces.value
                   + sp_dr.metrics.retraces.value - sp_r0)
    sp_misses = (sp_eng.metrics.cache_misses.value
                 + sp_dr.metrics.cache_misses.value - sp_m0)
    sp_parity = sp_outs == sp_refs
    sp_snap = sb.spec_snapshot()

    # E3 — mid-storm drafter kill: the pair must degrade to
    # primary-only decode (loud, decision-recorded), every stream still
    # emitting EXACTLY the reference tokens, both page ledgers clean
    ck_eng = spec_engine()
    ck_dr = spec_engine(prefix_share=False)
    crouter = DecodeRouter([ck_eng], drafters=[ck_dr], draft_k=spec_k,
                           max_waiting=n_streams).start()
    cb = crouter.batchers[0]
    cb.eos_id = -1
    crouter.warmup()
    pad_primary(ck_eng)
    ckstreams = [crouter.submit_ids(p, max_new_tokens=max_new)
                 for p in prompts]
    deadline = time.monotonic() + 120
    while (cb.metrics.tokens_out_total.value < pd_slots
           and time.monotonic() < deadline):
        time.sleep(0.002)
    crouter.kill_drafter(0)    # demonstrably mid-storm: tokens landed,
    ckouts = [s.result(timeout=600) for s in ckstreams]   # many to go
    ck_degraded = cb.drafter is None
    ck_deaths = int(cb.metrics.drafter_deaths_total.value)
    crouter.stop()
    ck_leaks = [ck_eng.leak_check(), ck_dr.leak_check()]
    ck_parity = ckouts == sp_refs

    # E4 — the controller's speculation law on an INJECTED acceptance
    # trajectory (the idle E2 pair is the actuation target, so every
    # knob turn lands on real batchers): sustained low acceptance must
    # halve k, catastrophic acceptance must switch speculation OFF, and
    # a forced re-enable that regresses spec_waste must auto-revert —
    # each move decision-recorded through ServeController._actuate.
    class _SpecInject:
        """Real router surface (``__getattr__`` delegation keeps every
        actuation on the recorded controller path) with a scripted
        draft/accept counter stream replacing live speculation."""

        def __init__(self, router):
            self._router = router
            self.drafted = 0
            self.accepted = 0

        def __getattr__(self, name):
            return getattr(self._router, name)

        def feed(self, rate, n=1000):
            self.drafted += n
            self.accepted += int(n * rate)

        def control_snapshot(self):
            snap = self._router.control_snapshot()
            snap["speculation"] = dict(
                snap.get("speculation") or {},
                draft_tokens=self.drafted,
                accepted_tokens=self.accepted)
            return snap

    shim = _SpecInject(srouter)
    clk = [0.0]
    ctrl = ServeController(shim, interval_s=1.0, tracer=spec_trace[0],
                           clock=lambda: clk[0])
    k_path = [int(srouter.knob_values()["draft_k"])]

    def ctick(rate=None, dt=1.0):
        clk[0] += dt
        if rate is not None:
            shim.feed(rate)
        ctrl.step()
        k_path.append(int(srouter.knob_values().get("draft_k", -1)))

    ctick()                    # primes the counter deltas
    ctick(0.20)                # sustained low acceptance ...
    ctick(0.20)                # ... halves k: 6 -> 3
    clk[0] += 6                # clear the draft_k cooldown
    ctick(0.20)
    ctick(0.20)                # 3 -> 1
    clk[0] += 6
    ctick(0.10)
    ctick(0.10)                # catastrophic: speculation OFF (0)
    ctick(0.90)                # good window -> spec_waste baseline
    ctrl.inject("draft_k", spec_k, "bench revert probe")
    for _ in range(12):        # mid-band acceptance: the law stays
        ctick(0.50)            # silent while spec_waste regresses
    sp_k_final = int(srouter.knob_values().get("draft_k", -1))
    sp_reverts = int(ctrl.reverts_total)
    ctrl.stop()                # resolves stragglers: outcome recorded
    srouter.stop()
    sp_leaks = [sp_eng.leak_check(), sp_dr.leak_check()]
    sp_pages_clean = all(lk["ok"] and not lk["stream_owners"]
                         for lk in sp_leaks + ck_leaks)

    # draft -> verify chain integrity through the FILE round trip, plus
    # every controller/degrade decision chain, from one flush
    spec_path = spec_trace[0].flush()
    srecords = []
    with open(spec_path) as f:
        for line in f:
            line = line.strip()
            if line:
                srecords.append(json.loads(line))
    sp_report = validate_chains(
        srecords,
        [s.rid for s in sstreams] + [s.rid for s in ckstreams])
    sp_decisions = validate_decisions(srecords)

    # --------------------- phase F: disaggregated prefill/decode pools
    # The isolation claim (ROADMAP item 4, DistServe/Splitwise): when
    # prefill is expensive, interleaving it with decode on ONE engine
    # stalls every live stream for the full prefill cost, so the
    # inter-token tail inherits that cost; a prefill pool handing
    # finished pages to a decode pool moves the work off the decode
    # path — decode units only IMPORT pages (a cheap fixed-shape
    # scatter), so their tail stays flat.  As in phase E the cost is
    # synthetic but honest: every prefill dispatch is padded by a fixed
    # df_pad_s AFTER warmup, on BOTH setups, and the storm is the same
    # on both — mixed prompt lengths with a per-stream max_new spread,
    # so completions desynchronise and admissions land mid-decode (the
    # interleaved engine then cannot hide the prefill behind idle
    # slots).  Socket transport: the wire framing is part of the
    # measured decode-pool path, not a best case.
    df_pad_s = 0.05
    df_n = 32
    df_prompts = prompts[:df_n]
    df_max_new = [int(x) for x in rng.integers(8, max_new + 1, df_n)]

    def pad_prefill(engine):
        # after warmup, like pad_primary: compile time and the
        # retrace/miss ledgers stay untouched, only dispatch wall time
        for name in ("prefill_ids", "prefill_chunk"):
            orig = getattr(engine, name)

            def padded(*a, _orig=orig, **kw):
                out = _orig(*a, **kw)
                time.sleep(df_pad_s)
                return out
            setattr(engine, name, padded)

    dargs = parse_cli([], base=Args(
        model="bert-tiny", decode_slots=pd_slots,
        decode_max_len=pd_max_len, max_new_tokens=max_new,
        kv_page_sz=pd_page_sz, seed=args.seed, trace=True,
        trace_dir=trace_dir))

    # F1 — interleaved control: one paged engine doing both jobs.  Its
    # outputs are also the parity reference (greedy decode is weight-
    # deterministic; the pools must reproduce it token for token).
    il_eng = PagedDecodeEngine(dargs, tokenizer=tok, mesh=None,
                               buckets=buckets)
    il_b = DecodeBatcher(il_eng, max_waiting=df_n).start()
    il_b.eos_id = -1
    il_b.warmup()
    il_r0 = il_eng.metrics.retraces.value
    il_m0 = il_eng.metrics.cache_misses.value
    pad_prefill(il_eng)
    il_streams = [il_b.submit_ids(p, max_new_tokens=mn)
                  for p, mn in zip(df_prompts, df_max_new)]
    il_outs = [s.result(timeout=600) for s in il_streams]
    il_snap = il_b.snapshot()
    il_b.stop()
    il_retraces = il_eng.metrics.retraces.value - il_r0
    il_misses = il_eng.metrics.cache_misses.value - il_m0
    il_leak = il_eng.leak_check()
    il_itok_p50 = il_snap["decode"]["intertoken_ms"]["p50"]
    il_itok_p99 = il_snap["decode"]["intertoken_ms"]["p99"]

    # F2 — the pool split: 1 prefill + 2 decode engines, same storm
    dengines = [PagedDecodeEngine(dargs, tokenizer=tok, mesh=None,
                                  buckets=buckets) for _ in range(3)]
    for e in dengines[1:]:
        e.tracer = dengines[0].tracer
    drouter = DisaggDecodeRouter(dengines, prefill_engines=1,
                                 max_waiting=df_n,
                                 transport="socket").start()
    for u in drouter._units:
        u.eos_id = -1
    drouter.warmup()
    df_r0 = sum(e.metrics.retraces.value for e in dengines)
    df_m0 = sum(e.metrics.cache_misses.value for e in dengines)
    for e in dengines:
        pad_prefill(e)  # decode units never call these — the point
    df_streams = [drouter.submit_ids(p, max_new_tokens=mn)
                  for p, mn in zip(df_prompts, df_max_new)]
    df_outs = [s.result(timeout=600) for s in df_streams]
    # snapshot BEFORE the kill leg: the isolation numbers are the
    # healthy storm's; PrefillWorker never records inter-token gaps, so
    # the merged latency block IS the decode pool's histogram
    df_snap = drouter.control_snapshot()
    df_itok_p50 = df_snap["latency"]["inter_token_p50_ms"]
    df_itok_p99 = df_snap["latency"]["inter_token_p99_ms"]
    df_ttft_p99 = df_snap["latency"]["ttft_p99_ms"]
    df_frames_ok = sum(s.frames_ok for s in drouter._servers.values())
    df_frames_err = sum(s.frames_err for s in drouter._servers.values())
    df_parity = df_outs == il_outs

    # F3 — mid-storm decode-replica kill on the WARM router (the prefix
    # index is hot from F2, so re-submitted prompts take the full-hit
    # handoff path: COW-source custody rides the boundary too).  The
    # victim's orphans re-home through the front door — re-prefill,
    # second handoff — and must still emit exactly the reference tokens.
    dk_n = 24
    dk_v0 = int(drouter._units[1].metrics.tokens_out_total.value)
    dk_streams = [drouter.submit_ids(p, max_new_tokens=mn)
                  for p, mn in zip(df_prompts[:dk_n], df_max_new[:dk_n])]
    deadline = time.monotonic() + 120
    while (int(drouter._units[1].metrics.tokens_out_total.value)
           < dk_v0 + 5 and time.monotonic() < deadline):
        time.sleep(0.002)
    drouter.kill(1, RuntimeError("bench decode-pool chaos"))
    dk_outs = [s.result(timeout=600) for s in dk_streams]
    dk_parity = dk_outs == il_outs[:dk_n]
    df_retraces = sum(e.metrics.retraces.value for e in dengines) - df_r0
    df_misses = (sum(e.metrics.cache_misses.value for e in dengines)
                 - df_m0)
    df_health = drouter.health_summary()
    drouter.stop()
    # survivor ledgers only: the victim's allocator died with its cache
    # (the established kill contract — see the paged kill storm above)
    df_leaks = {i: dengines[i].leak_check() for i in (0, 2)}
    df_clean = all(lk["ok"] and not lk["stream_owners"]
                   for lk in list(df_leaks.values()) + [il_leak])

    # pool-boundary chain integrity through the FILE round trip
    df_path = dengines[0].tracer.flush()
    dfrecords = []
    with open(df_path) as f:
        for line in f:
            line = line.strip()
            if line:
                dfrecords.append(json.loads(line))
    df_report = validate_chains(
        dfrecords,
        [s.rid for s in df_streams] + [s.rid for s in dk_streams])

    # ------------------------------------------------------------- gates
    if speedup < 2.0:
        failures.append(f"decode tokens/s/chip only {speedup:.2f}x the "
                        "re-prefill baseline (gate: >= 2x)")
    if retraces_post != 0 or misses_post != 0:
        failures.append(f"{retraces_post} post-warmup retraces / "
                        f"{misses_post} compile-cache misses (gate: 0)")
    if kill_retraces != 0:
        failures.append(f"{kill_retraces} retraces in the kill storm "
                        "(gate: 0 — both replicas warmed)")
    if intertoken_p99 is None or intertoken_p99 > p99_budget:
        failures.append(f"inter-token p99 {intertoken_p99} ms over the "
                        f"{p99_budget} ms budget")
    if occupancy_mean is None or occupancy_mean < 0.8:
        failures.append(f"mean slot occupancy {occupancy_mean} under the "
                        "0.8 continuous-batching gate")
    if not parity_ok:
        failures.append("re-prefill baseline diverged from cached decode "
                        "(argmax) — the speedup comparison is invalid")
    if not kill_parity:
        failures.append("mid-storm kill duplicated or lost tokens "
                        "(continuations != single-engine reference)")
    if report["incomplete"]:
        failures.append(f"{len(report['incomplete'])} incomplete hop "
                        "chains through the kill storm")
    if report["requeued"] < 1 or report["re_prefilled"] < 1:
        failures.append("the kill never exercised requeue/re-prefill — "
                        "the chaos leg proved nothing")
    if paged_peak < 3 * slot_peak:
        failures.append(
            f"paged layout peaked at {paged_peak} concurrent streams vs "
            f"{slot_peak} for the slot layout at equal --kv_hbm_mb "
            "(gate: >= 3x on the 80%-shared mix)")
    if not pd_parity:
        failures.append("paged storm diverged from the slot-cache "
                        "baseline (greedy continuations must be "
                        "token-identical)")
    if hit_prefills != 0:
        failures.append(f"full prefix hit ran {hit_prefills} prefill "
                        "forward(s) (gate: structurally zero)")
    if pd_retraces != 0 or pd_misses != 0:
        failures.append(f"{pd_retraces} retraces / {pd_misses} compile "
                        "misses on the paged path post-warmup (gate: 0)")
    if not drained_clean:
        failures.append(f"paged storm leaked pages at drain: {leak}")
    if not pk_parity:
        failures.append("paged kill storm duplicated or lost tokens "
                        "(re-prefilled survivors must match the "
                        "slot-cache baseline)")
    if pk_requeued < 1:
        failures.append("the paged kill never requeued a stream — the "
                        "re-attach leg proved nothing")
    if not pk_clean:
        failures.append(f"paged kill storm leaked pages on the "
                        f"survivor: {pk_leak}")
    if sp_speedup < 1.8:
        failures.append(
            f"speculative decode only {sp_speedup:.2f}x primary-only "
            "tokens/s (gate: >= 1.8x at the calibrated "
            f"{small_step_s / tiny_step_s:.1f}x primary/drafter cost "
            "ratio)")
    if not sp_parity:
        failures.append("speculative decode diverged from primary-only "
                        "(greedy verify must be BITWISE identical)")
    if sp_retraces != 0 or sp_misses != 0:
        failures.append(f"{sp_retraces} retraces / {sp_misses} compile "
                        "misses across the speculation pair post-warmup "
                        "(gate: 0 — drafter decode, verify, commit all "
                        "warmed)")
    if not sp_pages_clean:
        failures.append("speculation legs leaked pages: "
                        f"pair={sp_leaks} kill={ck_leaks}")
    if not ck_degraded or ck_deaths < 1:
        failures.append("mid-storm drafter kill never degraded the pair "
                        "to primary-only (the chaos leg proved nothing)")
    if not ck_parity:
        failures.append("drafter-kill continuations diverged from the "
                        "primary-only reference (degrade must preserve "
                        "exact tokens)")
    if sp_report["incomplete"]:
        failures.append(f"{len(sp_report['incomplete'])} incomplete hop "
                        "chains through the speculation storms")
    if sp_report["speculated"] < 1 or not sp_report["accept_rate"]:
        failures.append("trace round trip shows no speculated chains — "
                        "the draft/verify hops never reached the file")
    if not (3 in k_path and 0 in k_path):
        failures.append(f"controller never adapted k on the injected "
                        f"low-acceptance stream (k path {k_path})")
    if sp_reverts < 1 or sp_k_final != 0:
        failures.append(f"regressing re-enable was not auto-reverted "
                        f"(reverts={sp_reverts}, draft_k={sp_k_final})")
    if sp_decisions["incomplete"]:
        failures.append(f"{len(sp_decisions['incomplete'])} incomplete "
                        "decision chains (every actuation needs action "
                        "-> outcome)")
    if sp_decisions["by_knob"].get("draft_k", 0) < 3:
        failures.append("fewer than 3 draft_k decisions recorded — the "
                        "adaptation demo did not go through _actuate")
    df_pad_ms = df_pad_s * 1e3
    if il_itok_p99 is None or il_itok_p99 < df_pad_ms:
        failures.append(
            f"interleaved control inter-token p99 {il_itok_p99} ms never "
            f"inherited the {df_pad_ms:.0f} ms prefill pad — the "
            "isolation comparison measured nothing")
    if df_itok_p99 is None or df_itok_p99 >= df_pad_ms:
        failures.append(
            f"disaggregated decode-pool inter-token p99 {df_itok_p99} ms "
            f"not isolated from the {df_pad_ms:.0f} ms prefill pad "
            "(gate: decode units must never eat a prefill)")
    if not df_parity:
        failures.append("disaggregated storm diverged from the "
                        "interleaved reference (pool split must be "
                        "token-invisible)")
    if not dk_parity:
        failures.append("decode-replica kill duplicated or lost tokens "
                        "(re-homed orphans must match the interleaved "
                        "reference)")
    if df_retraces != 0 or df_misses != 0 or il_retraces != 0 \
            or il_misses != 0:
        failures.append(
            f"disagg phase retraced post-warmup (pools {df_retraces}/"
            f"{df_misses}, interleaved {il_retraces}/{il_misses}; "
            "gate: 0 — every engine warms both roles)")
    if df_frames_err != 0 or df_frames_ok < df_n:
        failures.append(
            f"socket handoff frames ok={df_frames_ok} err="
            f"{df_frames_err} (gate: every healthy-storm stream crosses "
            "the wire cleanly)")
    if df_report["incomplete"]:
        failures.append(f"{len(df_report['incomplete'])} incomplete hop "
                        "chains through the disaggregated storms")
    if df_report["handed_off"] != df_n + dk_n:
        failures.append(
            f"{df_report['handed_off']}/{df_n + dk_n} chains crossed "
            "the pool boundary via a handoff hop (gate: all of them)")
    if df_report["requeued"] < 1 or df_report["re_prefilled"] < 1:
        failures.append("the decode-pool kill never requeued/"
                        "re-prefilled a stream — the recovery leg "
                        "proved nothing")
    if not df_clean:
        failures.append("disagg phase leaked pages: "
                        f"survivors={df_leaks} interleaved={il_leak}")

    result = {
        "metric": "decode_smoke",
        "streams": n_streams,
        "slots": engine.slots,
        "max_new_tokens": max_new,
        "prompt_lens": [int(lens.min()), int(lens.max())],
        "decode": {
            "tokens_out": int(tokens_out),
            "elapsed_sec": round(decode_sec, 3),
            "tokens_per_sec_per_chip": round(decode_tps_chip, 1),
            "intertoken_ms_p50": snap["decode"]["intertoken_ms"]["p50"],
            "intertoken_ms_p99": intertoken_p99,
            "ttft_ms_p50": snap["decode"]["ttft_ms"]["p50"],
            "slot_occupancy_mean": occupancy_mean,
            "slot_reuse_ms_p50": snap["replica"]["slot_reuse_ms"]["p50"],
            "retraces_post_warmup": int(retraces_post),
            "kv": snap["kv"],
        },
        "reprefill_baseline": {
            "tokens_out": int(base_tokens),
            "elapsed_sec": round(baseline_sec, 3),
            "tokens_per_sec_per_chip": round(baseline_tps_chip, 1),
            "argmax_parity_with_cached": bool(parity_ok),
        },
        "speedup_vs_reprefill": round(speedup, 2),
        "kill_storm": {
            "replicas": 2,
            "token_parity_with_reference": bool(kill_parity),
            "retraces": int(kill_retraces),
            "requeued_to_survivor": int(requeued_in),
            "chains_checked": report["checked"],
            "chains_complete": report["complete"],
            "chains_requeued": report["requeued"],
            "chains_re_prefilled": report["re_prefilled"],
        },
        "paged_storm": {
            "streams": n_shared_storm,
            "shared_fraction": 0.8,
            "shared_prefix_tokens": len(shared_prefix),
            "page_sz": pd_page_sz,
            "kv_hbm_mb": round(budget_mb, 3),
            "slot_layout_slots": int(slot_cap),
            "slot_peak_live": int(slot_peak),
            "paged_pages": int(paged_eng.n_pages),
            "paged_peak_live": int(paged_peak),
            "concurrency_gain": round(paged_peak / max(slot_peak, 1), 2),
            "token_parity_with_slot_baseline": bool(pd_parity),
            "full_hit_prefill_forwards": int(hit_prefills),
            "full_hit_ttft_ms": round(hit_ttft_ms, 2),
            "retraces_post_warmup": int(pd_retraces),
            "pages": paged_snap["kv"]["pages"],
            "prefix": paged_snap["kv"]["prefix"],
            "leak_check": leak,
            "kill": {
                "replicas": 2,
                "token_parity_with_slot_baseline": bool(pk_parity),
                "requeued_to_survivor": int(pk_requeued),
                "survivor_prefix_hits": pk_hits,
                "survivor_leak_check": pk_leak,
            },
        },
        "speculation": {
            "draft_k": spec_k,
            "streams": n_streams,
            "max_new_tokens": max_new,
            "drafter_model": "bert-tiny",
            "primary_cost_model": "bert-small",
            "drafter_step_ms": round(tiny_step_s * 1e3, 3),
            "primary_step_ms": round(small_step_s * 1e3, 3),
            "cost_ratio": round(small_step_s / tiny_step_s, 2),
            "primary_only_tokens_per_sec": round(sp_base_tps, 1),
            "speculative_tokens_per_sec": round(sp_tps, 1),
            "speedup": round(sp_speedup, 2),
            "accept_rate": round(sp_snap["accept_rate"], 4),
            "rounds": sp_snap["rounds"],
            "draft_tokens": sp_snap["draft_tokens"],
            "accepted_tokens": sp_snap["accepted_tokens"],
            "token_parity_with_primary_only": bool(sp_parity),
            "retraces_post_warmup": int(sp_retraces),
            "compile_misses_post_warmup": int(sp_misses),
            "leak_checks": sp_leaks,
            "chains": {"checked": sp_report["checked"],
                       "complete": sp_report["complete"],
                       "speculated": sp_report["speculated"],
                       "accept_rate": sp_report["accept_rate"]},
            "drafter_kill": {
                "degraded_to_primary_only": bool(ck_degraded),
                "drafter_deaths": ck_deaths,
                "token_parity_with_primary_only": bool(ck_parity),
                "leak_checks": ck_leaks,
            },
            "controller": {
                "k_path": k_path,
                "final_draft_k": sp_k_final,
                "reverts": sp_reverts,
                "decisions_checked": sp_decisions["checked"],
                "decisions_complete": sp_decisions["complete"],
                "decisions_by_knob": sp_decisions["by_knob"],
            },
        },
        "disaggregation": {
            "engines": len(dengines),
            "pools": df_snap["by_pool"],
            "transport": "socket",
            "streams": df_n,
            "prefill_pad_ms": round(df_pad_ms, 1),
            "interleaved_intertoken_ms_p50": il_itok_p50,
            "interleaved_intertoken_ms_p99": il_itok_p99,
            "decode_pool_intertoken_ms_p50": df_itok_p50,
            "decode_pool_intertoken_ms_p99": df_itok_p99,
            "decode_pool_ttft_ms_p99": df_ttft_p99,
            "isolation_gain_p99": round(
                il_itok_p99 / df_itok_p99, 2) if df_itok_p99 else None,
            "token_parity_with_interleaved": bool(df_parity),
            "frames_ok": int(df_frames_ok),
            "frames_err": int(df_frames_err),
            "retraces_post_warmup": int(df_retraces),
            "handoffs": int(df_health["handoffs"]),
            "handoff_failures": int(df_health["handoff_failures"]),
            "chains": {"checked": df_report["checked"],
                       "complete": df_report["complete"],
                       "handed_off": df_report["handed_off"],
                       "requeued": df_report["requeued"],
                       "re_prefilled": df_report["re_prefilled"]},
            "kill": {
                "victim_pool": "decode",
                "streams": dk_n,
                "token_parity_with_interleaved": bool(dk_parity),
            },
            "survivor_leak_checks": {str(i): lk
                                     for i, lk in df_leaks.items()},
        },
        "p99_budget_ms": p99_budget,
        "model": args.model,
        "kv_dtype": engine.kv_snapshot()["kv_dtype"],
        "devices": n_chips,
        "platform": jax.devices()[0].platform,
        "gates": {
            "speedup_ge_2x": speedup >= 2.0,
            "zero_post_warmup_retraces": retraces_post == 0
            and misses_post == 0 and kill_retraces == 0,
            "intertoken_p99_under_budget": bool(
                intertoken_p99 is not None
                and intertoken_p99 <= p99_budget),
            "slot_occupancy_ge_0.8": bool(occupancy_mean is not None
                                          and occupancy_mean >= 0.8),
            "kill_chains_complete_no_dup_no_loss": bool(
                kill_parity and not report["incomplete"]),
            "paged_concurrency_ge_3x": bool(paged_peak >= 3 * slot_peak),
            "paged_token_parity": bool(pd_parity and pk_parity),
            "paged_full_hit_zero_prefill": hit_prefills == 0,
            "paged_zero_post_warmup_retraces": bool(
                pd_retraces == 0 and pd_misses == 0),
            "paged_zero_leaked_pages": bool(drained_clean and pk_clean),
            "spec_speedup_ge_1.8x": bool(sp_speedup >= 1.8),
            "spec_token_parity": bool(sp_parity and ck_parity),
            "spec_zero_post_warmup_retraces": bool(
                sp_retraces == 0 and sp_misses == 0),
            "spec_zero_leaked_pages": bool(sp_pages_clean),
            "spec_chains_complete": bool(
                not sp_report["incomplete"]
                and sp_report["speculated"] >= 1),
            "spec_controller_adapts_k": bool(
                3 in k_path and 0 in k_path and sp_reverts >= 1
                and sp_k_final == 0),
            "spec_decision_chains_complete": bool(
                not sp_decisions["incomplete"]
                and sp_decisions["by_knob"].get("draft_k", 0) >= 3),
            "disagg_decode_p99_isolated": bool(
                il_itok_p99 is not None and df_itok_p99 is not None
                and il_itok_p99 >= df_pad_ms
                and df_itok_p99 < df_pad_ms),
            "disagg_token_parity": bool(df_parity and dk_parity),
            "disagg_zero_post_warmup_retraces": bool(
                df_retraces == 0 and df_misses == 0
                and il_retraces == 0 and il_misses == 0),
            "disagg_wire_frames_clean": bool(
                df_frames_err == 0 and df_frames_ok >= df_n),
            "disagg_chains_complete_all_handed_off": bool(
                not df_report["incomplete"]
                and df_report["handed_off"] == df_n + dk_n),
            "disagg_kill_requeues_through_front_door": bool(
                df_report["requeued"] >= 1
                and df_report["re_prefilled"] >= 1),
            "disagg_zero_leaked_pages": bool(df_clean),
        },
        "failures": failures,
    }
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, out_path)
    print(json.dumps({k: v for k, v in result.items()
                      if k not in ("decode", "reprefill_baseline",
                                   "paged_storm", "speculation",
                                   "disaggregation")}))
    if failures:
        sys.exit("decode smoke FAILED:\n  - " + "\n  - ".join(failures)
                 + f"\n  see {out_path}")


def serve_load_smoke(argv) -> None:
    """``--serve-load``: closed-loop SLO gate for the multi-replica router.

    A Poisson arrival storm (``--serve_load_qps``, mixed lengths spanning
    3 buckets) is driven through a :class:`ReplicaRouter` over
    ``--serve_load_replicas`` engines while the smoke injects the failures
    the router exists to survive:

    - **mid-storm replica kill** (worker dies, beats stop — the SIGKILL
      shape at replica granularity): the router must eject it, requeue its
      queued + in-flight requests onto survivors, and — after the smoke
      relaunches it — reintegrate it only after a fresh bucket warmup;
    - **mid-storm rolling checkpoint swap**: one replica drained + swapped
      at a time, under load, with ZERO post-warmup retraces;
    - **an overload burst** (short deadlines, arrival >> service) that must
      walk ALL admission tiers: backpressure waits, shed-lowest-slack, and
      hard rejects, each recorded per tier.

    Then a **packed phase** (PR 9): the same seeded short-request storm
    (every request well under 64 tokens — the Chinese-emotion query shape)
    run CLOSED-LOOP twice over fresh pools, once padded
    (``serve_pack=off``) and once packed (``serve_pack=on``), with a
    mid-storm replica kill + relaunch on the packed run.  Gates: packed
    real-token throughput >= ``--serve_pack_ratio`` x the padded path,
    per-request logit parity between the runs (exact argmax where the
    padded top-2 margin is meaningful, max |diff| under 1e-3), token-level
    fill >= ``--serve_pack_fill``, ZERO post-warmup retraces on both pools
    (the packed path holds ONE compiled shape), and zero lost accepted
    requests through the kill.

    The storm runs TRACED (PR 10): every request mints a ``request_id``
    at admission and records hops through queue, pack placement,
    dispatch, eject-time requeue/re-pack and completion — and the smoke
    gates that every accepted request's hop chain is COMPLETE
    (reconstructable by ``trace_tpu.py request <id>``: one admit, one
    terminal, nothing after it), including at least one packed-phase request
    that crossed the mid-storm kill via re-pack.

    Gates (non-zero exit on any violation): zero LOST accepted requests (a
    request may succeed or deadline-fail, never vanish or surface a replica
    error), p99 latency at the target QPS under ``--serve_load_p99_ms``,
    zero post-warmup retraces across the pool, ejection-to-recovery under
    ``--serve_load_recovery_s``, a completed rolling swap with zero
    rollbacks, every admission tier engaged during the burst, complete
    hop chains incl. >=1 re-packed through the kill, and the packed-phase
    gates above.
    Snapshot: ``results/serve_load_smoke.json``.  Deterministic and
    CPU-safe like ``--serve`` (synthesized texts, seeded arrivals).
    """
    import random
    import tempfile
    import threading
    import time

    import jax

    from pdnlp_tpu.data.tokenizer import WordPieceTokenizer, build_vocab
    from pdnlp_tpu.serve import (
        InferenceEngine, LoadShedError, QueueFullError, ReplicaRouter,
    )
    from pdnlp_tpu.serve.batcher import DeadlineExceeded
    from pdnlp_tpu.train import checkpoint as ckpt_mod
    from pdnlp_tpu.utils.config import Args, parse_cli, pop_cli_flag

    argv, n_requests = pop_cli_flag(argv, "--serve_load_requests", 240, int)
    argv, qps = pop_cli_flag(argv, "--serve_load_qps", 120.0, float)
    argv, n_replicas = pop_cli_flag(argv, "--serve_load_replicas", 3, int)
    argv, p99_budget = pop_cli_flag(argv, "--serve_load_p99_ms", 1500.0,
                                    float)
    argv, recovery_bound = pop_cli_flag(argv, "--serve_load_recovery_s",
                                        20.0, float)
    argv, deadline_ms = pop_cli_flag(argv, "--serve_load_deadline_ms",
                                     8000.0, float)
    # 3600 requests: long enough that steady-state budget flushes dominate
    # the fill/throughput numbers over the timing-driven partials (ramp,
    # kill hop, tail) — the gates need headroom on a loaded CI host, not
    # a photo finish
    argv, pack_n = pop_cli_flag(argv, "--serve_pack_requests", 3600, int)
    argv, pack_ratio_floor = pop_cli_flag(argv, "--serve_pack_ratio", 1.5,
                                          float)
    argv, pack_fill_floor = pop_cli_flag(argv, "--serve_pack_fill", 0.85,
                                         float)
    argv, out_path = pop_cli_flag(
        argv, "--serve_load_out",
        os.path.join("results", "serve_load_smoke.json"))
    from pdnlp_tpu.obs.export import load_records
    from pdnlp_tpu.obs.request import chains, validate_chains

    # bert-tiny default (like --kernels): the gate measures ROUTER behavior
    # — ejection, requeue, tiers, swap — not model throughput; a bigger
    # model only slows the chaos loop without sharpening any assertion.
    # Tracing is ON: the hop-chain gate reconstructs every accepted
    # request's life from the flushed span files.
    # jaxlint: disable=L1 — the hop-chain gate reads this dir after the run
    trace_dir = tempfile.mkdtemp(prefix="pdnlp-serve-load-trace-")
    args = parse_cli(argv, base=Args(model="bert-tiny", trace=True,
                                     trace_dir=trace_dir))

    # deterministic mixed-length traffic across the 32/64/128 buckets
    chars = "天地人你我他好坏大小上下来去爱恨喜怒哀乐高兴悲伤讨厌愤怒"
    rng = random.Random(args.seed)
    lengths = [10, 24, 48, 60, 100, 120]
    texts = ["".join(rng.choice(chars)
                     for _ in range(lengths[i % len(lengths)]))
             for i in range(n_requests)]
    if os.path.exists(args.data_path) or os.path.exists(args.vocab_path):
        from pdnlp_tpu.data.tokenizer import get_or_build_vocab

        tok = WordPieceTokenizer(get_or_build_vocab(args))
    else:
        tok = WordPieceTokenizer(build_vocab(texts, size=256))

    buckets = (32, 64, 128)
    batch_size = 8
    max_queue = 64
    # one mesh slice per replica when the host has the devices; otherwise
    # independent plain-jit engines (the CPU-test shape)
    devices = list(jax.devices())
    per = len(devices) // n_replicas
    groups = [None] * n_replicas
    if per >= 1 and len(devices) >= n_replicas > 1:
        from pdnlp_tpu.parallel import make_mesh

        groups = [make_mesh(devices=devices[i * per:(i + 1) * per])
                  for i in range(n_replicas)]

    def factory(index: int) -> InferenceEngine:
        return InferenceEngine(args, tokenizer=tok, mesh=groups[index])

    engines = [factory(i) for i in range(n_replicas)]
    ckpt_path = ckpt_mod.latest(args.output_dir)
    if ckpt_path:
        try:
            for e in engines:
                e.load_checkpoint(ckpt_path)
        except Exception as exc:  # noqa: BLE001 — init weights are fine
            print(f"checkpoint {ckpt_path} not loadable ({exc}); "
                  "serving init weights", file=sys.stderr)
            ckpt_path = None
    # the main storm/burst pins the PADDED path: its tier gates (burst
    # sized at max_queue*3 REQUESTS) are calibrated in request units, and
    # on TPU `auto` would resolve packed and rescale admission to token
    # units out from under them — the packed phase below pins its own
    # modes explicitly
    router = ReplicaRouter(
        engines, engine_factory=factory, buckets=buckets,
        max_batch_size=batch_size, max_wait_ms=5.0, max_queue=max_queue,
        backpressure_wait_ms=10.0, default_deadline_ms=deadline_ms,
        serve_pack="off",
        stall_timeout=2.0, poll_interval=0.05, checkpoint_path=ckpt_path)
    router.start()
    if not router.wait_ready(600):
        sys.exit("serve-load smoke FAILED: replicas never finished warmup")

    # the rolling-swap artifact: the pool's own weights, re-published
    # through the manifest path (same shapes -> swap must not retrace)
    # jaxlint: disable=L1 — swap artifact must outlive the swap thread
    swap_dir = tempfile.mkdtemp(prefix="pdnlp-serve-load-")
    swap_path = os.path.join(swap_dir, "swap-cls.msgpack")
    ckpt_mod.save_params(swap_path,
                         {"params": jax.device_get(router.engine(0).params)})

    victim = n_replicas - 1
    kill_at, swap_at, relaunch_at = (n_requests // 3, n_requests // 2,
                                     (2 * n_requests) // 3)
    outcomes = {"ok": 0, "deadline": 0, "shed": 0, "rejected": 0,
                "lost": 0}
    swap_report: dict = {}
    swap_thread = None
    futs = []
    storm_t0 = time.monotonic()
    t_next = time.monotonic()
    for i in range(n_requests):
        if i == kill_at:
            # strand real work on the victim: a quick unpaced burst fills
            # every replica's queues, THEN the kill lands — the zero-lost
            # gate must cover requeued + retried requests, not an idle
            # replica's no-op death.  Guarded like every other submit: on
            # a slow host the backlog may already sit in the shed/reject
            # band, and that is an outcome to record, not a crash
            for j in range(2 * batch_size * n_replicas):
                try:
                    futs.append(router.submit(texts[(i + j) % len(texts)]))
                except LoadShedError:
                    outcomes["shed"] += 1
                except QueueFullError:
                    outcomes["rejected"] += 1
            router.kill_replica(victim, "crash")
        if i == relaunch_at:
            # the monitor needs one poll tick to classify the crash; the
            # relaunch API refuses to replace a live replica
            t_eject = time.monotonic() + 5.0
            while router.states[victim] != "ejected" \
                    and time.monotonic() < t_eject:
                time.sleep(0.01)
            router.relaunch(victim)
        if i == swap_at:
            # the rolling swap drains replicas one at a time — it must
            # run UNDER load, so it rides its own thread while arrivals
            # keep coming
            swap_thread = threading.Thread(
                target=lambda: swap_report.update(
                    router.swap_checkpoint(swap_path)))
            swap_thread.start()
        t_next += rng.expovariate(qps)  # Poisson arrivals at the target QPS
        time.sleep(max(0.0, t_next - time.monotonic()))
        try:
            futs.append(router.submit(texts[i]))
        except LoadShedError:
            outcomes["shed"] += 1
        except QueueFullError:
            outcomes["rejected"] += 1
    for f in futs:
        try:
            f.result(timeout=60)
            outcomes["ok"] += 1
        except DeadlineExceeded:
            outcomes["deadline"] += 1
        except LoadShedError:  # accepted, then shed while queued once the
            outcomes["shed"] += 1  # pool hit the shed band — by design
        except Exception:  # noqa: BLE001 — replica error/timeout = LOST
            outcomes["lost"] += 1
    if swap_thread is not None:
        swap_thread.join(timeout=60)
    storm_elapsed = time.monotonic() - storm_t0
    achieved_qps = len(futs) / storm_elapsed
    p99 = router.metrics.request_latency_ms.percentile(99)
    # the relaunched replica's warmup (fresh engine -> fresh compiles) may
    # outlast the storm tail; reintegration must COMPLETE before the gates
    # read recovery/reintegration counters
    if not router.wait_ready(300):
        sys.exit("serve-load smoke FAILED: relaunched replica never "
                 "finished its reintegration warmup")
    recovery = router.metrics.recovery_sec.snapshot()

    # ---- overload burst: every admission tier must engage + record ----
    burst_n = max_queue * 3
    burst_outcomes = {"ok": 0, "deadline": 0, "shed": 0, "rejected": 0,
                      "lost": 0}
    burst_lock = threading.Lock()
    burst_rids: list = []  # accepted burst requests join the chain gate

    def burster(k: int) -> None:
        fs = []
        for j in range(burst_n // 3):
            # every 3rd arrival carries a deadline under the shed tier's
            # slack floor: once the pool is in the shed band, those are
            # the lowest-slack requests and must be shed first
            dl = 8.0 if j % 3 == 0 else 150.0
            try:
                fs.append(router.submit(texts[(k + j) % len(texts)],
                                        deadline_ms=dl))
            except LoadShedError:
                with burst_lock:
                    burst_outcomes["shed"] += 1
            except QueueFullError:
                with burst_lock:
                    burst_outcomes["rejected"] += 1
        with burst_lock:
            burst_rids.extend(f.rid for f in fs)
        for f in fs:
            try:
                f.result(timeout=30)
                key = "ok"
            except DeadlineExceeded:
                key = "deadline"
            except LoadShedError:
                key = "shed"
            except Exception:  # noqa: BLE001
                key = "lost"
            with burst_lock:
                burst_outcomes[key] += 1

    bursters = [threading.Thread(target=burster, args=(k,))
                for k in range(3)]
    for t in bursters:
        t.start()
    for t in bursters:
        t.join(timeout=120)

    snap = router.snapshot()
    router.stop(drain=False)
    adm = snap["router"]["admission"]
    retraces_post = router.retraces_post_warmup

    # ---- hop-chain gate, storm half: flush the span file and validate
    # every ACCEPTED request's chain through the same offline path
    # `trace_tpu.py request <id>` uses (file round trip included)
    tracer = engines[0].tracer
    storm_trace = tracer.flush()
    storm_records = load_records(storm_trace)
    storm_rids = [f.rid for f in futs] + burst_rids
    storm_chains = validate_chains(storm_records, storm_rids)
    storm_chains["incomplete"] = dict(
        list(storm_chains["incomplete"].items())[:5])  # bounded report
    tracer.clear()  # the packed phases validate their own windows

    # ---- packed phase: short-request storm, packed vs padded pools ----
    # the throughput half of ROADMAP item 1: every request is well under
    # 64 tokens (the dominant production shape), so the padded path burns
    # most of each forward on [PAD] while the packed path bin-packs many
    # requests per 128-token row.  Closed-loop (window-bounded) submission
    # over the SAME seeded request sequence measures pool capacity; the
    # packed run also absorbs a mid-storm kill + relaunch.
    prng = random.Random(args.seed + 1)
    short_lengths = [4, 7, 10, 14, 18, 22]  # chars -> ~6..24 tokens
    ptexts = ["".join(prng.choice(chars)
                      for _ in range(short_lengths[i % len(short_lengths)]))
              for i in range(pack_n)]
    pids = [tok.encode_ids(t, max(buckets)) for t in ptexts]
    pack_tokens = sum(len(i) for i in pids)
    mean_tok = pack_tokens / max(1, len(pids))

    def run_pack_storm(mode: str, kill: bool) -> dict:
        tracer.clear()  # this phase's chain gate reads its own window
        engines2 = [factory(i) for i in range(n_replicas)]
        flush_tokens = engines2[0].pad_rows(batch_size) * max(buckets)
        if mode == "on":  # window ~= 2 packed flushes per replica, in
            per_rep = max(1, int(flush_tokens / mean_tok))  # request units
        else:
            per_rep = engines2[0].pad_rows(batch_size)
        window = 2 * n_replicas * per_rep
        # a 25ms age bound (vs the storm's 5ms): the phase is deadline-
        # free and throughput-gated, so partial aged flushes at the ramp,
        # the kill hop, and the tail should not eat the fill number
        r2 = ReplicaRouter(
            engines2, engine_factory=factory, buckets=buckets,
            max_batch_size=batch_size, max_wait_ms=25.0,
            max_queue=4 * window, serve_pack=mode, stall_timeout=2.0,
            poll_interval=0.05, checkpoint_path=ckpt_path)
        r2.start()
        if not r2.wait_ready(600):
            sys.exit(f"serve-load smoke FAILED: packed-phase pool "
                     f"(serve_pack={mode}) never finished warmup")
        victim2 = n_replicas - 1
        kill_at, relaunch_at = pack_n // 3, (2 * pack_n) // 3
        from collections import deque

        futs2: list = [None] * pack_n
        rids2: list = []
        inflight: deque = deque()
        lost = 0
        t0 = time.monotonic()
        for i, ids in enumerate(pids):
            if kill and i == kill_at:
                r2.kill_replica(victim2, "crash")
            if kill and i == relaunch_at:
                t_eject = time.monotonic() + 5.0
                while r2.states[victim2] != "ejected" \
                        and time.monotonic() < t_eject:
                    time.sleep(0.01)
                r2.relaunch(victim2)
            # deadline-free submits: the admission ladder never sheds
            # deadline-free work, so every request must complete — any
            # exception (queue-full would mean a mis-sized window) is LOST
            futs2[i] = r2.submit_ids(list(ids))
            rids2.append(futs2[i].rid)
            inflight.append(i)
            while len(inflight) >= window:
                j = inflight.popleft()
                try:
                    futs2[j] = futs2[j].result(timeout=120)
                except Exception:  # noqa: BLE001
                    futs2[j] = None
        while inflight:
            j = inflight.popleft()
            try:
                futs2[j] = futs2[j].result(timeout=120)
            except Exception:  # noqa: BLE001
                futs2[j] = None
        elapsed = time.monotonic() - t0
        lost = sum(1 for f in futs2 if f is None)
        if kill and not r2.wait_ready(300):
            sys.exit("serve-load smoke FAILED: packed-phase relaunch "
                     "never finished its reintegration warmup")
        snap2 = r2.snapshot()
        fills = [s["fill_ratio"] for s in snap2["replicas"].values()]
        fill_n = sum(f["count"] for f in fills)
        fill_mean = (sum((f["mean"] or 0.0) * f["count"] for f in fills)
                     / fill_n if fill_n else None)
        retr = r2.retraces_post_warmup
        r2.stop(drain=False)
        # hop-chain gate, phase half: every accepted request's chain must
        # be complete; the kill run must show >=1 requeue (re-pack when
        # packed) crossing the ejection with the SAME id
        phase_records = tracer.records()
        chain_report = validate_chains(phase_records, rids2)
        example = None
        if chain_report["requeued"]:
            # one indexed pass (chains), not a full-stream rescan per rid
            by_id = chains(phase_records)
            for rid in rids2:
                hops = [(r.get("attrs") or {})
                        for r in by_id.get(rid, [])]
                if any(h.get("hop") == "requeue" for h in hops):
                    example = {"request_id": rid,
                               "hops": [h.get("hop") for h in hops]}
                    break
        chain_report["incomplete"] = dict(
            list(chain_report["incomplete"].items())[:5])
        return {
            "serve_pack": mode,
            "request_tracing": {**chain_report, "example_requeued": example},
            "requests": pack_n,
            "real_tokens": pack_tokens,
            "elapsed_s": round(elapsed, 3),
            "tokens_per_s": round(pack_tokens / elapsed, 1),
            "requests_per_s": round(pack_n / elapsed, 1),
            "window": window,
            "lost": lost,
            "fill_mean": (round(fill_mean, 4)
                          if fill_mean is not None else None),
            "batches": sum(s["batches_total"]
                           for s in snap2["replicas"].values()),
            "retraces_post_warmup": retr,
            "kill": ({"victim": victim2,
                      "ejections": snap2["router"]["ejections_total"],
                      "requeued": snap2["router"]["requeued_total"],
                      "retries": snap2["router"]["retries_total"]}
                     if kill else None),
            "_logits": futs2,
        }

    padded_run = run_pack_storm("off", kill=False)
    packed_run = run_pack_storm("on", kill=True)
    # per-request parity between the two runs: exact argmax wherever the
    # padded top-2 margin is meaningful (offset segments reduce over
    # shifted key indices -> ulp-level drift, never semantic), tight
    # absolute bound everywhere
    import numpy as np

    parity = {"compared": 0, "argmax_mismatch": 0, "max_abs_diff": 0.0}
    for a, b in zip(padded_run.pop("_logits"), packed_run.pop("_logits")):
        if a is None or b is None:
            continue
        parity["compared"] += 1
        parity["max_abs_diff"] = max(parity["max_abs_diff"],
                                     float(np.abs(a - b).max()))
        top2 = np.sort(a)[-2:]
        if np.argmax(a) != np.argmax(b) and top2[1] - top2[0] > 1e-4:
            parity["argmax_mismatch"] += 1
    parity["max_abs_diff"] = round(parity["max_abs_diff"], 9)
    pack_ratio = (packed_run["tokens_per_s"]
                  / max(1e-9, padded_run["tokens_per_s"]))

    result = {
        "metric": "serve_load_smoke",
        "requests": n_requests,
        "target_qps": qps,
        "achieved_qps": round(achieved_qps, 1),
        "replicas": n_replicas,
        "device_groups": [g is not None for g in groups],
        "buckets": list(buckets),
        "batch_size": batch_size,
        "max_queue": max_queue,
        "deadline_ms": deadline_ms,
        "storm": outcomes,
        "latency_ms_p50":
            router.metrics.request_latency_ms.percentile(50),
        "latency_ms_p99": p99,
        "p99_budget_ms": p99_budget,
        "kill": {
            "victim": victim,
            "ejections": snap["router"]["ejections_total"],
            "requeued": snap["router"]["requeued_total"],
            "retries": snap["router"]["retries_total"],
            "reintegrations": snap["router"]["reintegrations_total"],
            "recovery_sec_max": recovery["max"],
            "recovery_bound_s": recovery_bound,
        },
        "swap": {
            "swapped": swap_report.get("swapped"),
            "rolled_back": swap_report.get("rolled_back"),
            "skipped": swap_report.get("skipped"),
        },
        "retraces_post_warmup": retraces_post,
        "burst": {"requests": 3 * (burst_n // 3), **burst_outcomes},
        "admission": adm,
        "request_tracing": {"storm": storm_chains},
        "packed_phase": {
            "padded": padded_run,
            "packed": packed_run,
            "tokens_throughput_ratio": round(pack_ratio, 2),
            "ratio_floor": pack_ratio_floor,
            "fill_floor": pack_fill_floor,
            "parity": parity,
        },
        "checkpoint": ckpt_path,
        "model": args.model,
        "serve_dtype": router.engine(0).dtype_label,
        "devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
        "metrics": snap,
    }

    failures = []
    if outcomes["lost"] or burst_outcomes["lost"]:
        failures.append(
            f"LOST accepted requests: storm {outcomes['lost']} / burst "
            f"{burst_outcomes['lost']} (every accepted request must "
            "complete or deadline-fail)")
    if outcomes["deadline"] + outcomes["shed"] + outcomes["rejected"] \
            > n_requests // 10:
        failures.append(
            f"storm shed too much at the target QPS: {outcomes} (the pool "
            "must absorb the configured load, not shed it)")
    if p99 is not None and p99 > p99_budget:
        failures.append(f"p99 latency {p99:.1f}ms over the "
                        f"{p99_budget:.0f}ms budget at {qps} QPS")
    if retraces_post != 0:
        failures.append(f"{retraces_post} post-warmup retraces (expected "
                        "0 across kill, relaunch and rolling swap)")
    if snap["router"]["ejections_total"] < 1 \
            or snap["router"]["reintegrations_total"] < 1:
        failures.append("the killed replica was not ejected+reintegrated "
                        f"(ejections {snap['router']['ejections_total']}, "
                        "reintegrations "
                        f"{snap['router']['reintegrations_total']})")
    if snap["router"]["requeued_total"] \
            + snap["router"]["retries_total"] < 1:
        failures.append("the kill stranded no requests — requeue/retry "
                        "was never exercised (requeued "
                        f"{snap['router']['requeued_total']}, retries "
                        f"{snap['router']['retries_total']})")
    if recovery["count"] < 1 or (recovery["max"] or 0) > recovery_bound:
        failures.append(f"ejection->recovery {recovery['max']}s outside "
                        f"the {recovery_bound}s bound")
    if len(swap_report.get("swapped") or []) < max(1, n_replicas - 1) \
            or swap_report.get("rolled_back"):
        failures.append(f"rolling swap incomplete: {swap_report}")
    for tier in ("backpressure_waits", "shed", "rejected"):
        if adm[tier] < 1:
            failures.append(f"admission tier {tier!r} never engaged "
                            f"during the burst ({adm})")
    # ---- packed-phase gates ----
    if pack_ratio < pack_ratio_floor:
        failures.append(
            f"packed tokens-throughput {packed_run['tokens_per_s']}/s is "
            f"only {pack_ratio:.2f}x the padded path "
            f"({padded_run['tokens_per_s']}/s) — floor "
            f"{pack_ratio_floor}x at the short-request mix")
    if parity["argmax_mismatch"] or parity["max_abs_diff"] > 1e-3:
        failures.append(f"packed-vs-padded per-request parity broken: "
                        f"{parity}")
    if parity["compared"] < pack_n:
        failures.append(f"parity compared only {parity['compared']}"
                        f"/{pack_n} requests (lost futures?)")
    if packed_run["fill_mean"] is None \
            or packed_run["fill_mean"] < pack_fill_floor:
        failures.append(f"packed fill {packed_run['fill_mean']} under the "
                        f"{pack_fill_floor} floor")
    if packed_run["retraces_post_warmup"] \
            or padded_run["retraces_post_warmup"]:
        failures.append(
            "packed-phase post-warmup retraces (packed "
            f"{packed_run['retraces_post_warmup']}, padded "
            f"{padded_run['retraces_post_warmup']}) — the packed path "
            "must hold ONE compiled shape")
    if packed_run["lost"] or padded_run["lost"]:
        failures.append(f"packed phase LOST requests through the kill "
                        f"(packed {packed_run['lost']}, padded "
                        f"{padded_run['lost']})")
    pk = packed_run["kill"]
    if pk["ejections"] < 1 or pk["requeued"] + pk["retries"] < 1:
        failures.append("the packed-phase kill stranded no work — "
                        f"eject/re-pack was never exercised ({pk})")
    # ---- hop-chain gates: every accepted request reconstructable ----
    for label, rep in (("storm", storm_chains),
                       ("padded", padded_run["request_tracing"]),
                       ("packed", packed_run["request_tracing"])):
        if rep["complete"] < rep["checked"]:
            failures.append(
                f"{label} phase: {rep['checked'] - rep['complete']} "
                "accepted request(s) without a complete hop chain "
                f"(first: {list(rep['incomplete'].items())[:2]})")
    if packed_run["request_tracing"]["repacked"] < 1:
        failures.append(
            "no packed-phase request crossed the mid-storm kill via "
            "re-pack with a joinable request_id (requeued="
            f"{packed_run['request_tracing']['requeued']})")

    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, out_path)
    print(json.dumps({k: v for k, v in result.items() if k != "metrics"}))
    # the smoke's temp dirs (span files, swap artifact) were consumed
    # above — a CI host must not accrete one per run
    import shutil

    shutil.rmtree(trace_dir, ignore_errors=True)
    shutil.rmtree(swap_dir, ignore_errors=True)
    if failures:
        sys.exit("serve-load smoke FAILED:\n  - " + "\n  - ".join(failures)
                 + f"\n  see {out_path}")


def replay_smoke(argv) -> None:
    """``--replay``: trace-driven load replay — the controller-vs-static
    proving ground (ROADMAP item 2's gate).

    Phase 0 — **record**: a seeded Poisson storm runs through a traced
    router; the flushed span file's ``admit`` hops (timestamp + tokens +
    deadline — ``serve.replay.arrivals_from_trace``) become the base
    arrival schedule.  The recording is reconstructed through the SAME
    file round trip ``trace_tpu.py`` uses, so any trace a production run
    flushed is replayable the same way.

    Phase 1 — **replay matrix**: the schedule is reshaped
    (``serve.replay.shape_arrivals``) into three traffic shapes —
    ``steady`` (1x), ``diurnal`` ramp (3x, trough -> peak -> trough), and
    ``flash`` crowd (5x with a mid-replay burst at 8x the base rate,
    plus the chaos replica kill + warmup-gated relaunch mid-storm) — and
    each shape is driven open-loop through three POOL CONFIGURATIONS over
    identical engines: two plausible static hand-tunings ("latency":
    1ms flush age + aggressive 10ms hedging; "throughput": 150ms flush
    age, no hedging) and the **controller** configuration
    (:class:`~pdnlp_tpu.serve.controller.ServeController` actuating flush
    age, hedge, admission and warm-standby replica count live).

    Phase 2 — **bad-actuation probe**: a short controller run where the
    smoke injects a harmful actuation (``max_wait_ms`` to its clamp
    ceiling) through the controller's own ``_actuate`` choke point, then
    gates that the evaluation window AUTO-REVERTS it and puts the knob in
    a backoff hold; a quiet tail + load burst then exercises the full
    scale-down -> warm-standby -> warmup-gated reactivation cycle.

    Gates (non-zero exit on any violation):

    - **frontier**: per shape, no static configuration dominates the
      controller on BOTH axes (p99 AND goodput, with noise margins), and
      the controller's geomean score (goodput_tokens_per_s / p99_ms
      across shapes) strictly beats every static's — adapting must win
      the p99 x throughput frontier, not just tie the best hand-tuning
      per shape;
    - **SLO** (the ``--serve-load`` discipline): ZERO lost accepted
      requests in every run, controller p99 under ``--replay_p99_ms``
      on every shape, ZERO post-warmup retraces everywhere — including
      through the kill/relaunch and the scale-down/reactivation cycles;
    - **decisions**: every controller actuation carries a complete
      cause -> action -> outcome chain (``obs.decision.validate_decisions``
      over the flushed file, plus a real ``trace_tpu.py decisions`` exit-0
      round trip), the probe's injected actuation is reverted within its
      evaluation window, and the probe exercised >= 1 scale-down AND
      >= 1 reactivation;
    - **chaos**: each flash run ejected + reintegrated the killed replica
      with >= 1 requeue/retry.

    Deterministic per host (seeded arrivals, seeded shapes; absolute
    throughput scales with the host's forward time — the comparisons are
    within-run).  Snapshot: ``results/replay_smoke.json``.
    """
    import math
    import tempfile
    import threading
    import time

    import jax

    from pdnlp_tpu.data.tokenizer import WordPieceTokenizer, build_vocab
    from pdnlp_tpu.obs.decision import validate_decisions
    from pdnlp_tpu.obs.export import load_records
    from pdnlp_tpu.serve import InferenceEngine, ReplicaRouter
    from pdnlp_tpu.serve.controller import (
        KnobSpec, ServeController, default_specs,
    )
    from pdnlp_tpu.serve.replay import (
        arrivals_from_trace, replay, shape_arrivals, synth_arrivals,
    )
    from pdnlp_tpu.utils.config import Args, parse_cli, pop_cli_flag

    argv, n_requests = pop_cli_flag(argv, "--replay_requests", 3600, int)
    argv, base_qps = pop_cli_flag(argv, "--replay_qps", None, float)
    argv, n_replicas = pop_cli_flag(argv, "--replay_replicas", 3, int)
    argv, deadline_ms = pop_cli_flag(argv, "--replay_deadline_ms", 250.0,
                                     float)
    argv, p99_budget = pop_cli_flag(argv, "--replay_p99_ms", 2000.0, float)
    argv, out_path = pop_cli_flag(
        argv, "--replay_out", os.path.join("results", "replay_smoke.json"))

    # jaxlint: disable=L1 — the replay gate reads this dir after the run
    trace_dir = tempfile.mkdtemp(prefix="pdnlp-replay-trace-")
    args = parse_cli(argv, base=Args(model="bert-tiny", trace=True,
                                     trace_dir=trace_dir))

    import random as _random

    chars = "天地人你我他好坏大小上下来去爱恨喜怒哀乐高兴悲伤讨厌愤怒"
    vocab_texts = ["".join(_random.Random(args.seed).choice(chars)
                           for _ in range(24)) for _ in range(64)]
    tok = WordPieceTokenizer(build_vocab(vocab_texts, size=256))

    buckets = (32,)
    batch_size = 8
    max_queue = 512  # token of head-room: overload policy is the knobs'

    def factory(index: int) -> InferenceEngine:
        return InferenceEngine(args, tokenizer=tok, mesh=None)

    # ONE engine pool reused across every run: each router start re-runs
    # the warmup on its worker (compile-cache hits after the first), so
    # eleven pools cost four compiles, and the per-run retrace baselines
    # stay exact
    engines = [factory(i) for i in range(n_replicas)]
    tracer = engines[0].tracer

    def build_router(cfg: dict) -> ReplicaRouter:
        return ReplicaRouter(
            engines, engine_factory=factory, buckets=buckets,
            max_batch_size=batch_size,
            max_wait_ms=cfg.get("max_wait_ms", 5.0),
            hedge_ms=cfg.get("hedge_ms"),
            max_queue=max_queue, serve_pack="off",
            stall_timeout=2.0, poll_interval=0.02)

    #: the replay controller tuned for second-scale runs: tight interval,
    #: short evaluation windows and cooldowns, a wide declared-safe flush
    #: age range (the probe's injected 250ms IS in range — in range and
    #: harmful is exactly what the evaluation loop exists to catch)
    def build_controller(router: ReplicaRouter, manage_flush: bool = True,
                         scale_patience: int = 8) -> ServeController:
        specs = default_specs()
        specs["max_wait_ms"] = KnobSpec(
            "max_wait_ms", 1.0, 250.0, cooldown_s=0.4, hysteresis=0.3,
            signal="p99_ms", noise_floor=8.0)
        specs["hedge_ms"] = KnobSpec(
            "hedge_ms", 5.0, 2000.0, cooldown_s=0.4, hysteresis=0.25,
            signal="p99_ms", noise_floor=8.0)
        specs["backpressure_at"] = KnobSpec(
            "backpressure_at", 8, 10 ** 9, cooldown_s=0.5, hysteresis=0.2,
            signal="slo_pressure", noise_floor=0.02, integer=True)
        specs["replicas"] = KnobSpec(
            "replicas", 1, n_replicas, cooldown_s=0.8, hysteresis=0.0,
            signal="p99_ms", noise_floor=8.0, integer=True)
        specs["hedge_ms"].lo = 25.0
        return ServeController(
            router, interval_s=0.12, min_replicas=1, specs=specs,
            eval_window_s=0.7, revert_margin=0.3, hold_base_s=3.0,
            hold_cap_s=30.0, hedge_factor=0.3, fill_fraction=0.12,
            wait_budget_ms=15.0, scale_patience=scale_patience,
            util_low=0.12,
            util_high=0.75, util_batch=0.5, ewma_alpha=0.5,
            manage_flush=manage_flush, tracer=tracer)

    configs = {
        "static_latency": {"max_wait_ms": 1.0, "hedge_ms": 5.0},
        "static_throughput": {"max_wait_ms": 150.0, "hedge_ms": None},
        "controller": {"max_wait_ms": 5.0, "hedge_ms": 50.0},
    }
    shapes = [("steady", 1.0, False), ("diurnal", 4.0, False),
              ("flash", 5.0, True)]

    # ---- phase 0: record a seeded storm, reconstruct it from the trace
    tracer.clear()
    rec_router = build_router({"max_wait_ms": 5.0}).start()
    if not rec_router.wait_ready(600):
        sys.exit("replay smoke FAILED: recording pool never warmed up")
    # calibrate the storm to the HOST's measured capacity: the shapes
    # must sit in the regime where batching and adaptation matter (steady
    # comfortable, diurnal peak near the small-batch cliff, flash over
    # it) on fast and slow CI hosts alike.  Explicit --replay_qps pins it.
    forward_ts = []
    probe_ids = [[tok.cls_id, 7, 9, tok.sep_id]] * batch_size
    for _ in range(15):
        t0 = time.perf_counter()
        # infer_ids returns HOST numpy (the engine materializes inside its
        # own forward span) — the delta below is real wall time, not an
        # async-dispatch enqueue measurement
        engines[0].infer_ids(probe_ids, buckets[0], rows=batch_size)
        forward_ts.append(time.perf_counter() - t0)  # jaxlint: disable=R4 — infer_ids blocked on host results above
    forward_ms = sorted(forward_ts)[len(forward_ts) // 2] * 1e3
    capacity_rps = n_replicas * batch_size / (forward_ms / 1e3)
    if base_qps is None:
        # 0.28 x full-batch capacity puts the storm INSIDE the regime the
        # comparison is about: batches execute as fixed padded shapes, so
        # a 1ms flush age burns whole padded batches on 1-3 real rows and
        # its EFFECTIVE capacity is a fraction of the batched pool's —
        # steady sits above that fraction, the diurnal peak well above it,
        # and the flash crowd above even the batched ceiling
        base_qps = round(min(1200.0, max(150.0, 0.28 * capacity_rps)), 1)
    rec_schedule = synth_arrivals(n_requests, base_qps,
                                  lengths=(6, 9, 12, 16, 20, 26),
                                  deadline_ms=deadline_ms, seed=args.seed)
    rec_report = replay(rec_router.submit_ids, rec_schedule)
    rec_router.stop(drain=False)
    trace_path = tracer.flush()
    base = arrivals_from_trace(load_records(trace_path))
    tracer.clear()
    if len(base) < 0.98 * n_requests:
        sys.exit(f"replay smoke FAILED: recording reconstructed only "
                 f"{len(base)}/{n_requests} arrivals from the trace")
    # determinism: the trace -> schedule reconstruction is pure
    base2 = arrivals_from_trace(load_records(trace_path))
    if [a.as_tuple() for a in base] != [a.as_tuple() for a in base2]:
        sys.exit("replay smoke FAILED: arrival reconstruction is not "
                 "deterministic over the same trace")

    # ---- phase 1: the shapes x configs matrix over identical engines
    def run_one(config_name: str, cfg: dict, shape: str, speed: float,
                kill: bool) -> dict:
        tracer.clear()
        # flash_factor 20: the crowd must OVERLOAD the pool long enough to
        # build deadline-scale backlog, or every configuration absorbs it
        # and the comparison degenerates to ties
        schedule = shape_arrivals(base, shape, speed=speed,
                                  flash_factor=20.0)
        router = build_router(cfg).start()
        if not router.wait_ready(600):
            sys.exit(f"replay smoke FAILED: {config_name}/{shape} pool "
                     "never warmed up")
        controller = None
        if config_name == "controller":
            controller = build_controller(router).start()
        victim = n_replicas - 1
        kill_at, relaunch_at = len(schedule) // 2, (3 * len(schedule)) // 4
        state = {"relaunched": False}

        def on_tick(i: int) -> None:
            if not kill:
                return
            if i == kill_at:
                router.kill_replica(victim, "crash")
            elif i >= relaunch_at and not state["relaunched"]:
                if router.states[victim] == "ejected":
                    router.relaunch(victim)
                    state["relaunched"] = True

        rep = replay(router.submit_ids, schedule, on_tick=on_tick)
        if kill and not state["relaunched"] and \
                router.states[victim] == "ejected":
            router.relaunch(victim)  # tail kill: still prove reintegration
        if kill and not router.wait_ready(300):
            sys.exit(f"replay smoke FAILED: {config_name}/{shape} "
                     "relaunch never finished reintegration warmup")
        if controller is not None:
            controller.stop()
        snap = router.snapshot()
        p99 = router.metrics.request_latency_ms.percentile(99)
        retraces = router.retraces_post_warmup
        router.stop(drain=False)
        out = {
            "config": config_name, "shape": shape, "speed": speed,
            **rep.as_dict(),
            "p99_ms": round(p99, 2) if p99 is not None else None,
            "p50_ms": round(
                router.metrics.request_latency_ms.percentile(50) or 0, 2),
            "retraces_post_warmup": retraces,
            "hedges": snap["router"]["hedges_total"],
            "knobs_final": snap["knobs"],
            "kill": ({"ejections": snap["router"]["ejections_total"],
                      "requeued": snap["router"]["requeued_total"],
                      "retries": snap["router"]["retries_total"],
                      "reintegrations":
                          snap["router"]["reintegrations_total"]}
                     if kill else None),
        }
        if controller is not None:
            decisions = validate_decisions(tracer.records())
            decisions["incomplete"] = dict(
                list(decisions["incomplete"].items())[:5])
            out["controller"] = {
                "actuations": controller.actuations_total,
                "reverts": controller.reverts_total,
                "blocked": controller.blocked_total,
                "errors": controller.errors_total,
                "scale_downs": snap["router"]["scale_downs_total"],
                "scale_ups": snap["router"]["scale_ups_total"],
                "decisions": decisions,
            }
        return out

    def run_score(run: dict):
        p99 = run.get("p99_ms")
        if not p99 or not run.get("goodput_tokens_per_s"):
            return None
        return run["goodput_tokens_per_s"] / p99

    # two INTERLEAVED passes per cell, keep each cell's better pass for
    # the frontier (one loaded-host hiccup must not sink a cell — the
    # same discipline as --telemetry's interleaved arms); the SLO gates
    # below run over EVERY pass, kept or not
    runs: dict = {}
    all_runs: list = []
    for pass_i in range(2):
        for shape, speed, kill in shapes:
            for config_name, cfg in configs.items():
                key = f"{config_name}/{shape}"
                run = run_one(config_name, cfg, shape, speed, kill)
                run["pass"] = pass_i
                all_runs.append(run)
                prev = runs.get(key)
                s_new, s_old = run_score(run), \
                    run_score(prev) if prev else None
                if prev is None or (s_new or 0) > (s_old or 0):
                    runs[key] = run
                print(f"[replay] pass{pass_i} {key}: "
                      f"goodput {run['goodput_tokens_per_s']} tok/s  "
                      f"p99 {run['p99_ms']}ms  "
                      f"deadline {run['deadline']}  "
                      f"hedges {run['hedges']}", file=sys.stderr)

    # ---- phase 2: bad-actuation probe + scale cycle on a short schedule
    tracer.clear()
    probe_router = build_router(configs["controller"]).start()
    if not probe_router.wait_ready(600):
        sys.exit("replay smoke FAILED: probe pool never warmed up")
    # the probe isolates the injected actuation: the flush-age LAW is off,
    # so the injection is max_wait_ms's only writer and the auto-revert
    # (not a concurrent law actuation) is what restores it; the short
    # scale patience makes the quiet-tail drain-to-standby prompt
    probe_ctl = build_controller(probe_router, manage_flush=False,
                                 scale_patience=2).start()
    probe_schedule = shape_arrivals(base[: max(600, n_requests // 4)],
                                    "steady", speed=1.0)
    inject_at = len(probe_schedule) // 3
    injected = {"done": False}

    def probe_tick(i: int) -> None:
        if i == inject_at and not injected["done"]:
            # a harmful-but-in-range actuation through the controller's
            # own choke point: clamped, decision-recorded — and WRONG
            injected["done"] = probe_ctl.inject("max_wait_ms", 250.0)

    probe_rep = replay(probe_router.submit_ids, probe_schedule,
                       on_tick=probe_tick)
    # quiet tail: the scaling law must drain a replica to warm standby...
    deadline_t = time.monotonic() + 10.0
    while probe_router.standby_count < 1 and time.monotonic() < deadline_t:
        time.sleep(0.05)
    scale_down_seen = probe_router.standby_count >= 1
    # ...and a load burst must bring it back through the warmup gate
    burst_futs = []
    deadline_t = time.monotonic() + 15.0
    while probe_router.standby_count > 0 and time.monotonic() < deadline_t:
        # outpace the reduced pool so queue pressure actually builds (the
        # scale-up signal); admission refusals are outcomes, not errors
        for _ in range(100):
            try:
                burst_futs.append(probe_router.submit_ids(
                    [tok.cls_id, 7, 8, 9, tok.sep_id],
                    deadline_ms=30_000))
            except Exception:  # noqa: BLE001
                pass
        time.sleep(0.02)
    scale_up_seen = probe_router.standby_count == 0 and scale_down_seen
    if not probe_router.wait_ready(120):
        sys.exit("replay smoke FAILED: probe reactivation never finished "
                 "its warmup gate")
    burst_ok = sum(1 for f in burst_futs
                   if _silent_result(f) is not None)
    probe_ctl.stop()
    probe_snap = probe_router.snapshot()
    probe_retraces = probe_router.retraces_post_warmup
    probe_router.stop(drain=False)
    probe_trace = tracer.flush()
    probe_decisions = validate_decisions(load_records(probe_trace))
    probe_decisions["incomplete"] = dict(
        list(probe_decisions["incomplete"].items())[:5])
    # the reconstructability contract, through the REAL CLI surface
    import trace_tpu

    decisions_cli_rc = trace_tpu.main(["decisions", probe_trace])

    # ---- the frontier: per-shape non-domination + geomean score win
    score = run_score
    frontier = {"per_shape": {}, "geomean": {}}
    failures = []
    for config_name in configs:
        vals = []
        for shape, _, _ in shapes:
            s = score(runs[f"{config_name}/{shape}"])
            frontier["per_shape"].setdefault(shape, {})[config_name] = \
                round(s, 3) if s is not None else None
            vals.append(max(s or 1e-9, 1e-9))
        frontier["geomean"][config_name] = round(
            math.exp(sum(math.log(v) for v in vals) / len(vals)), 3)

    ctrl_geo = frontier["geomean"]["controller"]
    for static in ("static_latency", "static_throughput"):
        if ctrl_geo <= frontier["geomean"][static]:
            failures.append(
                f"frontier: controller geomean score {ctrl_geo} does not "
                f"beat {static} ({frontier['geomean'][static]}) — "
                "adapting lost to a hand-tuned constant")
        for shape, _, _ in shapes:
            c = runs[f"controller/{shape}"]
            s = runs[f"{static}/{shape}"]
            if c["p99_ms"] and s["p99_ms"] \
                    and s["p99_ms"] < c["p99_ms"] / 1.15 \
                    and s["goodput_tokens_per_s"] \
                    > c["goodput_tokens_per_s"] * 1.10:
                failures.append(
                    f"frontier: {static} DOMINATES the controller on "
                    f"{shape} (p99 {s['p99_ms']} vs {c['p99_ms']}ms, "
                    f"goodput {s['goodput_tokens_per_s']} vs "
                    f"{c['goodput_tokens_per_s']} tok/s)")

    # ---- SLO gates: the --serve-load discipline, EVERY pass (kept or not)
    for run in all_runs:
        key = f"{run['config']}/{run['shape']} (pass {run['pass']})"
        if run["lost"]:
            failures.append(f"{key}: {run['lost']} LOST accepted "
                            "request(s)")
        if run["retraces_post_warmup"]:
            failures.append(f"{key}: {run['retraces_post_warmup']} "
                            "post-warmup retraces")
        if run["kill"] is not None:
            k = run["kill"]
            if k["ejections"] < 1 or k["reintegrations"] < 1:
                failures.append(f"{key}: kill not ejected+reintegrated "
                                f"({k})")
            if k["requeued"] + k["retries"] < 1:
                failures.append(f"{key}: the kill stranded no work ({k})")
        if run["config"] == "controller":
            if run["p99_ms"] is None or run["p99_ms"] > p99_budget:
                failures.append(f"{key}: p99 {run['p99_ms']}ms over the "
                                f"{p99_budget}ms budget")
            dec = run["controller"]["decisions"]
            if dec["incomplete"]:
                failures.append(f"{key}: incomplete decision chains "
                                f"{dec['incomplete']}")
            if run["controller"]["actuations"] < 1:
                failures.append(f"{key}: the controller never actuated — "
                                "the loop is not closed")

    # ---- probe gates: auto-revert + hold + the standby cycle
    if not injected["done"]:
        failures.append("probe: the bad actuation was never injected")
    if probe_decisions["reverted"] < 1:
        failures.append(
            "probe: the injected bad actuation was NOT auto-reverted "
            f"within its evaluation window ({probe_decisions})")
    if probe_decisions["incomplete"]:
        failures.append(f"probe: incomplete decision chains "
                        f"{probe_decisions['incomplete']}")
    if decisions_cli_rc != 0:
        failures.append("probe: `trace_tpu.py decisions` could not "
                        "reconstruct a valid chain (exit "
                        f"{decisions_cli_rc})")
    if not scale_down_seen:
        failures.append("probe: low load never drained a replica to warm "
                        "standby")
    if not scale_up_seen:
        failures.append("probe: the load burst never reactivated the "
                        "standby replica")
    if probe_retraces:
        failures.append(f"probe: {probe_retraces} post-warmup retraces "
                        "through the scale-down/reactivation cycle")
    if probe_rep.lost:
        failures.append(f"probe: {probe_rep.lost} LOST requests")

    result = {
        "metric": "replay_smoke",
        "requests": n_requests,
        "base_qps": base_qps,
        "calibration": {"forward_ms": round(forward_ms, 3),
                        "capacity_rps": round(capacity_rps, 1)},
        "deadline_ms": deadline_ms,
        "replicas": n_replicas,
        "buckets": list(buckets),
        "batch_size": batch_size,
        "recording": {"submitted": rec_report.submitted,
                      "reconstructed": len(base),
                      "deterministic": True},
        "shapes": [{"shape": s, "speed": v, "kill": k}
                   for s, v, k in shapes],
        "configs": {k: {kk: vv for kk, vv in v.items()}
                    for k, v in configs.items()},
        "runs": runs,
        "frontier": frontier,
        "probe": {
            **probe_rep.as_dict(),
            "injected": injected["done"],
            "scale_down_seen": scale_down_seen,
            "scale_up_seen": scale_up_seen,
            "burst_completed": burst_ok,
            "retraces_post_warmup": probe_retraces,
            "actuations": probe_ctl.actuations_total,
            "reverts": probe_ctl.reverts_total,
            "holds": probe_ctl.snapshot()["holds_s"],
            "scale_downs": probe_snap["router"]["scale_downs_total"],
            "scale_ups": probe_snap["router"]["scale_ups_total"],
            "decisions": probe_decisions,
            "decisions_cli_exit": decisions_cli_rc,
        },
        "p99_budget_ms": p99_budget,
        "model": args.model,
        "devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
    }

    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, out_path)
    print(json.dumps({k: v for k, v in result.items() if k != "runs"}))
    import shutil

    shutil.rmtree(trace_dir, ignore_errors=True)
    if failures:
        sys.exit("replay smoke FAILED:\n  - " + "\n  - ".join(failures)
                 + f"\n  see {out_path}")


def fleet_smoke(argv) -> None:
    """``--fleet``: the multi-model fleet gate (ROADMAP item 4) — three
    proofs over one reused engine set (2x primary bf16, 1x candidate
    loading a deliberately-PERTURBED checkpoint, 1x cheap int8 of the
    same weights):

    **(a) shadow impact** — the same seeded storm runs through
    control (no shadow) and treatment (``--fleet_shadow``, default 20%
    shadow onto the bad candidate) fleets, INTERLEAVED twice per arm
    (loaded-CI discipline, same as ``--telemetry``), at a rate
    auto-calibrated to the host's measured forward capacity (explicit
    ``--fleet_qps`` pins it).  Gates: per-request argmax outcomes are
    IDENTICAL across every pass (the candidate's answers measurably
    differ — parity mismatches prove the comparison is real — yet no
    caller ever sees one), best-arm p99 within the latency margin,
    every chain (incl. every shadow duplicate's, terminating shadow-side)
    complete through the file round trip, zero post-warmup retraces.

    **(b) canary rollout** — two storms under a
    :class:`~pdnlp_tpu.serve.controller.ServeController` rollout law:
    a GOOD candidate (same checkpoint) advances the canary fraction up
    the :class:`RolloutPlan` steps on live shadow-parity evidence; then
    the BAD candidate is pushed to 25% via the controller's own
    ``inject`` choke point mid-storm and the law AUTO-ROLLS-BACK to 0
    (parity regression), draining the candidate's queue to the primary.
    Gates: good rollout reaches >= the second step with zero rollbacks;
    bad rollout ends at fraction 0 with >= 1 recorded rollback, zero
    lost requests, and complete decision chains both ways.

    **(c) degrade tier** — a back-to-back overload burst against a
    tight primary ladder, control (no cheap model: the pre-fleet ladder
    sheds it) vs treatment (degrade band re-routes to the int8 cheap
    pool).  Gates: control sheds >= 1; treatment sheds/rejects 0 with
    >= 1 degraded request, every degraded chain carrying its ``degrade``
    hop before dispatch, and the cheap model's per-model metrics showing
    exactly the shifted traffic.

    Snapshot: ``results/fleet_smoke.json`` (non-zero exit on any gate).
    """
    import dataclasses
    import tempfile
    import time

    import jax
    import numpy as np

    from pdnlp_tpu.data.tokenizer import WordPieceTokenizer, build_vocab
    from pdnlp_tpu.obs.decision import validate_decisions
    from pdnlp_tpu.obs.export import load_records
    from pdnlp_tpu.obs.request import validate_chains
    from pdnlp_tpu.serve import (
        FleetRouter, InferenceEngine, LoadShedError, QueueFullError,
        ReplicaRouter, RolloutPlan, ServeController,
    )
    from pdnlp_tpu.serve.controller import KnobSpec, default_specs
    from pdnlp_tpu.serve.replay import ids_for, replay, synth_arrivals
    from pdnlp_tpu.train import checkpoint as ckpt_mod
    from pdnlp_tpu.utils.config import Args, parse_cli, pop_cli_flag

    argv, n_requests = pop_cli_flag(argv, "--fleet_requests", 600, int)
    argv, base_qps = pop_cli_flag(argv, "--fleet_qps", None, float)
    argv, shadow_fraction = pop_cli_flag(argv, "--fleet_shadow", 0.2,
                                         float)
    argv, deadline_ms = pop_cli_flag(argv, "--fleet_deadline_ms",
                                     30_000.0, float)
    argv, p99_factor = pop_cli_flag(argv, "--fleet_p99_factor", 1.5,
                                    float)
    argv, p99_margin_ms = pop_cli_flag(argv, "--fleet_p99_margin_ms",
                                       25.0, float)
    argv, out_path = pop_cli_flag(
        argv, "--fleet_out", os.path.join("results", "fleet_smoke.json"))

    # jaxlint: disable=L1 — fleet gate reads traces/ckpts after the run
    trace_dir = tempfile.mkdtemp(prefix="pdnlp-fleet-trace-")
    # jaxlint: disable=L1 — fleet gate reads traces/ckpts after the run
    ckpt_dir = tempfile.mkdtemp(prefix="pdnlp-fleet-ckpt-")
    args = parse_cli(argv, base=Args(model="bert-tiny", trace=True,
                                     trace_dir=trace_dir))

    import random as _random

    chars = "天地人你我他好坏大小上下来去爱恨喜怒哀乐高兴悲伤讨厌愤怒"
    vocab_texts = ["".join(_random.Random(args.seed).choice(chars)
                           for _ in range(24)) for _ in range(64)]
    tok = WordPieceTokenizer(build_vocab(vocab_texts, size=256))
    buckets = (32,)
    batch_size = 8

    # ONE engine set reused across every phase (compile once): the
    # per-group checkpoint_path makes each router's warmup load the right
    # artifact onto its engines
    eng_prim = [InferenceEngine(args, tokenizer=tok, mesh=None)
                for _ in range(2)]
    eng_cand = [InferenceEngine(args, tokenizer=tok, mesh=None)]
    eng_cheap = [InferenceEngine(
        dataclasses.replace(args, serve_dtype="int8"),
        tokenizer=tok, mesh=None)]
    tracer = eng_prim[0].tracer

    # the good checkpoint = the shared init weights; the BAD candidate
    # checkpoint is the same tree with the classifier head's class axis
    # ROLLED by one (every leaf whose last dim is num_labels) —
    # shape-valid, loads cleanly, and every answer is deterministically
    # the wrong class (logits permuted), which is exactly the regression
    # shadow parity exists to catch
    host = jax.device_get(eng_prim[0].params)
    good_ckpt = os.path.join(ckpt_dir, "good-cls.msgpack")
    ckpt_mod.save(good_ckpt, host)
    bad_ckpt = os.path.join(ckpt_dir, "bad-cls.msgpack")
    n_labels = args.num_labels
    ckpt_mod.save(bad_ckpt, jax.tree_util.tree_map(
        lambda a: (np.roll(np.asarray(a), 1, axis=-1)
                   if np.asarray(a).ndim >= 1
                   and np.asarray(a).shape[-1] == n_labels
                   else np.asarray(a)), host))

    def make_group(mid, engines, ckpt_path, **kw):
        kw.setdefault("max_queue", 512)
        return ReplicaRouter(
            engines, buckets=buckets, max_batch_size=batch_size,
            max_wait_ms=5.0, stall_timeout=10.0, poll_interval=0.02,
            serve_pack="off", checkpoint_path=ckpt_path, model_id=mid,
            tracer=tracer, **kw)

    def start_fleet(fleet):
        fleet.start()
        if not fleet.wait_ready(600):
            sys.exit("fleet smoke FAILED: a pool never finished warmup")
        return fleet

    failures: list = []

    # ---- calibration (deflake): the storm rate rides the HOST's measured
    # forward capacity, so the shadow-impact comparison sits in the same
    # sub-saturation regime on fast and slow CI hosts alike
    warm = make_group("prod", eng_prim, good_ckpt)
    start_fleet(FleetRouter({"prod": warm}, primary="prod",
                            tracer=tracer)).stop(drain=False)
    probe_ids = [[tok.cls_id, 7, 9, tok.sep_id]] * batch_size
    forward_ts = []
    for _ in range(15):
        t0 = time.perf_counter()
        # infer_ids returns HOST numpy — real wall time, not an enqueue
        eng_prim[0].infer_ids(probe_ids, buckets[0], rows=batch_size)
        forward_ts.append(time.perf_counter() - t0)  # jaxlint: disable=R4 — infer_ids blocked on host results above
    forward_ms = sorted(forward_ts)[len(forward_ts) // 2] * 1e3
    capacity_rps = len(eng_prim) * batch_size / (forward_ms / 1e3)
    if base_qps is None:
        base_qps = round(min(800.0, max(100.0, 0.25 * capacity_rps)), 1)
    schedule = synth_arrivals(n_requests, base_qps,
                              lengths=(6, 9, 12, 16, 20, 26),
                              deadline_ms=deadline_ms, seed=args.seed)

    # ---------------------------------------------- (a) shadow impact
    def run_storm(shadow_frac: float, label: str) -> dict:
        tracer.clear()
        prim = make_group("prod", eng_prim, good_ckpt)
        cand = make_group("cand", eng_cand, bad_ckpt)
        fleet = start_fleet(FleetRouter(
            {"prod": prim, "cand": cand}, primary="prod",
            candidate="cand", shadow_fraction=shadow_frac, tracer=tracer))
        futs: list = []

        def submit(ids, deadline_ms=None):
            f = fleet.submit_ids(ids, deadline_ms=deadline_ms)
            futs.append(f)
            return f

        rep = replay(submit, schedule)
        fleet.stop(drain=True)
        chains_rep = validate_chains(load_records(tracer.flush()))
        chains_rep["incomplete"] = dict(
            list(chains_rep["incomplete"].items())[:5])
        out = {
            "label": label, "shadow_fraction": shadow_frac,
            **rep.as_dict(),
            "p99_ms": round(prim.metrics.request_latency_ms
                            .percentile(99) or 0.0, 2),
            "argmaxes": [int(np.argmax(f._logits))
                         if f._error is None and f._logits is not None
                         else None for f in futs],
            "retraces_post_warmup": fleet.retraces_post_warmup,
            "chains": {k: v for k, v in chains_rep.items()
                       if k != "incomplete"},
            "chains_incomplete": chains_rep["incomplete"],
            "fleet": fleet.metrics.snapshot(),
            "shadow": fleet.shadow_report.snapshot(),
        }
        print(f"[fleet] {label}: p99 {out['p99_ms']}ms  ok {rep.ok}"
              f"/{rep.submitted}  shadows {out['fleet']['shadows_total']}"
              f"  parity {out['shadow']['checked']} checked "
              f"{out['shadow']['mismatches']} mismatched",
              file=sys.stderr)
        return out

    arms: dict = {"control": [], "shadow": []}
    for i in range(2):  # interleaved passes (loaded-CI discipline)
        arms["control"].append(run_storm(0.0, f"control/pass{i}"))
        arms["shadow"].append(run_storm(shadow_fraction,
                                        f"shadow/pass{i}"))

    baseline_argmax = arms["control"][0]["argmaxes"]
    for arm in ("control", "shadow"):
        for run in arms[arm]:
            if run["argmaxes"] != baseline_argmax:
                diff = sum(1 for a, b in zip(run["argmaxes"],
                                             baseline_argmax) if a != b)
                failures.append(
                    f"(a) {run['label']}: caller-visible outcomes differ "
                    f"from the no-shadow control ({diff} of "
                    f"{len(baseline_argmax)} argmaxes)")
            if run["lost"] or run["deadline"] or run["shed"] \
                    or run["rejected"]:
                failures.append(f"(a) {run['label']}: outcome split not "
                                "clean under the calibrated storm "
                                f"({run['lost']} lost, {run['deadline']} "
                                f"deadline, {run['shed']} shed, "
                                f"{run['rejected']} rejected)")
            if run["retraces_post_warmup"]:
                failures.append(f"(a) {run['label']}: "
                                f"{run['retraces_post_warmup']} "
                                "post-warmup retraces")
            if run["chains_incomplete"]:
                failures.append(f"(a) {run['label']}: incomplete chains "
                                f"{run['chains_incomplete']}")
    control_p99 = min(r["p99_ms"] for r in arms["control"])
    shadow_p99 = min(r["p99_ms"] for r in arms["shadow"])
    if shadow_p99 > control_p99 * p99_factor + p99_margin_ms:
        failures.append(
            f"(a) shadow p99 {shadow_p99}ms exceeds the no-shadow "
            f"control's {control_p99}ms beyond the margin "
            f"(x{p99_factor} + {p99_margin_ms}ms)")
    expect_shadows = int(shadow_fraction * n_requests)
    for run in arms["shadow"]:
        got = run["fleet"]["shadows_total"]
        if abs(got - expect_shadows) > 1:
            failures.append(f"(a) {run['label']}: {got} shadows vs the "
                            f"{expect_shadows} the fraction promises")
        if run["shadow"]["mismatches"] < 1:
            failures.append(f"(a) {run['label']}: the perturbed candidate "
                            "produced ZERO argmax mismatches — the parity "
                            "comparison cannot be real")
        if run["chains"]["shadowed"] < got:
            failures.append(f"(a) {run['label']}: only "
                            f"{run['chains']['shadowed']} shadow chains "
                            f"for {got} shadow submissions")

    # ------------------------------------- (b) canary rollout + rollback
    def rollout_controller(fleet, plan):
        specs = default_specs()
        specs["canary_fraction"] = KnobSpec(
            "canary_fraction", 0.0, 1.0, cooldown_s=0.25, hysteresis=0.0,
            signal="p99_ms", noise_floor=50.0)
        return ServeController(
            fleet, interval_s=0.05, specs=specs, rollout=plan,
            eval_window_s=0.4, revert_margin=1.0,
            manage_flush=False, manage_admission=False,
            manage_hedge=False, scale_patience=10 ** 6, tracer=tracer)

    def run_rollout(cand_ckpt: str, label: str, inject_frac, plan
                    ) -> dict:
        tracer.clear()
        prim = make_group("prod", eng_prim, good_ckpt)
        cand = make_group("cand", eng_cand, cand_ckpt)
        fleet = start_fleet(FleetRouter(
            {"prod": prim, "cand": cand}, primary="prod",
            candidate="cand",
            shadow_fraction=max(shadow_fraction, 0.25), tracer=tracer))
        ctl = rollout_controller(fleet, plan).start()
        futs: list = []
        inject_at = len(schedule) // 3
        injected = {"done": False}

        def on_tick(i: int) -> None:
            if inject_frac is not None and i == inject_at \
                    and not injected["done"]:
                # the optimistic-operator push, through the controller's
                # own choke point: clamped, decision-recorded — and WRONG
                injected["done"] = ctl.inject("canary_fraction",
                                              inject_frac)

        def submit(ids, deadline_ms=None):
            f = fleet.submit_ids(ids, deadline_ms=deadline_ms)
            futs.append(f)
            return f

        rep = replay(submit, schedule, on_tick=on_tick)
        # the law needs a few quiet ticks to finish judging (and the
        # rollback drain to land) after the storm's tail
        deadline_t = time.monotonic() + 5.0
        want_zero = inject_frac is not None
        while time.monotonic() < deadline_t:
            frac = fleet.canary_fraction
            if (want_zero and frac == 0.0) or \
                    (not want_zero and frac >= plan.steps[1]):
                break
            time.sleep(0.05)
        ctl.stop()
        fleet.stop(drain=True)
        lost = sum(1 for f in futs
                   if f._error is not None
                   and not isinstance(f._error, (LoadShedError,)))
        trace_path = tracer.flush()
        records = load_records(trace_path)
        chains_rep = validate_chains(records)
        chains_rep["incomplete"] = dict(
            list(chains_rep["incomplete"].items())[:5])
        decisions = validate_decisions(records)
        decisions["incomplete"] = dict(
            list(decisions["incomplete"].items())[:5])
        out = {
            "label": label, **rep.as_dict(), "lost_futures": lost,
            "injected": injected["done"],
            "final_fraction": fleet.canary_fraction,
            "canary_routed": fleet.metrics.canary_routed_total.value,
            "rollbacks": fleet.metrics.rollbacks_total.value,
            "rolled_back_requests":
                fleet.metrics.rolled_back_requests_total.value,
            "controller": {"actuations": ctl.actuations_total,
                           "rollbacks": ctl.rollbacks_total,
                           "reverts": ctl.reverts_total,
                           "errors": ctl.errors_total},
            "decisions": decisions,
            "chains": {k: v for k, v in chains_rep.items()
                       if k != "incomplete"},
            "chains_incomplete": chains_rep["incomplete"],
            "shadow": fleet.shadow_report.snapshot(),
            "retraces_post_warmup": fleet.retraces_post_warmup,
        }
        print(f"[fleet] {label}: fraction {out['final_fraction']}  "
              f"canary_routed {out['canary_routed']}  rollbacks "
              f"{out['rollbacks']}  actuations "
              f"{out['controller']['actuations']}", file=sys.stderr)
        return out

    good_plan = RolloutPlan(steps=(0.1, 0.25, 0.5), min_shadow_checked=10,
                            parity_tolerance=0.02, p99_factor=50.0,
                            patience=1)
    good_run = run_rollout(good_ckpt, "rollout/good", None, good_plan)
    bad_plan = RolloutPlan(steps=(0.25, 0.5, 1.0), min_shadow_checked=10,
                          parity_tolerance=0.02, p99_factor=50.0,
                          patience=2)
    bad_run = run_rollout(bad_ckpt, "rollout/bad", 0.25, bad_plan)

    if good_run["final_fraction"] < good_plan.steps[1]:
        failures.append(
            f"(b) good rollout stalled at fraction "
            f"{good_run['final_fraction']} (< step {good_plan.steps[1]}) "
            "— the law never advanced on clean parity evidence")
    if good_run["rollbacks"]:
        failures.append(f"(b) good rollout was rolled back "
                        f"{good_run['rollbacks']}x on clean evidence")
    if not bad_run["injected"]:
        failures.append("(b) the bad-canary fraction was never injected")
    if bad_run["final_fraction"] != 0.0 or bad_run["rollbacks"] < 1:
        failures.append(
            f"(b) the bad canary was NOT auto-rolled-back (final "
            f"fraction {bad_run['final_fraction']}, "
            f"{bad_run['rollbacks']} rollbacks)")
    if bad_run["canary_routed"] < 1:
        failures.append("(b) the injected fraction routed no caller "
                        "traffic — the rollback undid nothing real")
    for run in (good_run, bad_run):
        if run["lost"] or run["lost_futures"]:
            failures.append(f"(b) {run['label']}: {run['lost']} lost in "
                            f"replay, {run['lost_futures']} failed "
                            "futures — a rollout must never lose "
                            "accepted work")
        if run["decisions"]["incomplete"]:
            failures.append(f"(b) {run['label']}: incomplete decision "
                            f"chains {run['decisions']['incomplete']}")
        if run["chains_incomplete"]:
            failures.append(f"(b) {run['label']}: incomplete request "
                            f"chains {run['chains_incomplete']}")
        if run["retraces_post_warmup"]:
            failures.append(f"(b) {run['label']}: "
                            f"{run['retraces_post_warmup']} post-warmup "
                            "retraces")

    # --------------------------------------------------- (c) degrade tier
    def degrade_burst(with_cheap: bool, label: str) -> dict:
        tracer.clear()
        prim = make_group("prod", eng_prim, good_ckpt, max_queue=16,
                          backpressure_at=8,
                          degrade_at=10 if with_cheap else None,
                          shed_at=12, backpressure_wait_ms=1.0,
                          shed_slack_ms=2 * deadline_ms)
        groups = {"prod": prim}
        if with_cheap:
            groups["tiny"] = make_group("tiny", eng_cheap, good_ckpt)
        fleet = start_fleet(FleetRouter(
            groups, primary="prod",
            cheap="tiny" if with_cheap else None, tracer=tracer))
        futs: list = []
        shed = rejected = 0
        n_burst = 120
        for i in range(n_burst):  # back-to-back: the overload burst
            try:
                futs.append(fleet.submit_ids(
                    ids_for(schedule[i % len(schedule)], i),
                    deadline_ms=deadline_ms))
            except LoadShedError:
                shed += 1
            except QueueFullError:
                rejected += 1
        ok = lost = queued_shed = expired = 0
        for f in futs:
            try:
                f.result(timeout=deadline_ms / 1e3 + 10)
                ok += 1
            except LoadShedError:
                queued_shed += 1
            except Exception as e:  # noqa: BLE001
                if "Deadline" in type(e).__name__:
                    expired += 1
                else:
                    lost += 1
        fleet.stop(drain=True)
        chains_rep = validate_chains(load_records(tracer.flush()))
        chains_rep["incomplete"] = dict(
            list(chains_rep["incomplete"].items())[:5])
        snap = fleet.snapshot()
        out = {
            "label": label, "burst": n_burst, "ok": ok,
            "shed_on_arrival": shed, "shed_queued": queued_shed,
            "rejected": rejected, "deadline": expired, "lost": lost,
            "degraded": fleet.metrics.degraded_total.value,
            "degrade_fallthrough":
                fleet.metrics.degrade_fallthrough_total.value,
            "per_model_requests": {
                mid: snap["models"][mid]["router"]["requests_total"]
                for mid in snap["models"]},
            "chains": {k: v for k, v in chains_rep.items()
                       if k != "incomplete"},
            "chains_incomplete": chains_rep["incomplete"],
            "retraces_post_warmup": fleet.retraces_post_warmup,
        }
        print(f"[fleet] {label}: ok {ok}/{n_burst}  shed "
              f"{shed}+{queued_shed}  rejected {rejected}  degraded "
              f"{out['degraded']}", file=sys.stderr)
        return out

    control_burst = degrade_burst(False, "degrade/control")
    treat_burst = degrade_burst(True, "degrade/treatment")

    if control_burst["shed_on_arrival"] + control_burst["shed_queued"] \
            + control_burst["rejected"] < 1:
        failures.append("(c) the control burst never shed/rejected — the "
                        "overload is not an overload, nothing to absorb")
    if treat_burst["shed_on_arrival"] or treat_burst["shed_queued"] \
            or treat_burst["rejected"]:
        failures.append(
            f"(c) the degrade tier did NOT absorb the burst: "
            f"{treat_burst['shed_on_arrival']}+"
            f"{treat_burst['shed_queued']} shed, "
            f"{treat_burst['rejected']} rejected with a cheap model "
            "registered")
    if treat_burst["degraded"] < 1:
        failures.append("(c) no request was degraded — the band never "
                        "engaged")
    if treat_burst["lost"] or treat_burst["deadline"]:
        failures.append(f"(c) treatment lost {treat_burst['lost']} / "
                        f"expired {treat_burst['deadline']} — degraded "
                        "work must still complete")
    if treat_burst["chains"]["degraded"] != treat_burst["degraded"]:
        failures.append(
            f"(c) {treat_burst['degraded']} degrades counted but only "
            f"{treat_burst['chains']['degraded']} chains carry the "
            "degrade hop")
    if treat_burst["per_model_requests"].get("tiny", 0) \
            != treat_burst["degraded"]:
        failures.append(
            "(c) per-model metrics do not show the shift: cheap-model "
            f"requests {treat_burst['per_model_requests'].get('tiny')} "
            f"!= degraded {treat_burst['degraded']}")
    if treat_burst["chains_incomplete"]:
        failures.append(f"(c) incomplete chains "
                        f"{treat_burst['chains_incomplete']}")

    result = {
        "metric": "fleet_smoke",
        "requests": n_requests,
        "base_qps": base_qps,
        "calibration": {"forward_ms": round(forward_ms, 3),
                        "capacity_rps": round(capacity_rps, 1)},
        "deadline_ms": deadline_ms,
        "buckets": list(buckets),
        "batch_size": batch_size,
        "shadow_fraction": shadow_fraction,
        "shadow_impact": {
            "control_p99_ms": control_p99,
            "shadow_p99_ms": shadow_p99,
            "p99_gate": f"<= x{p99_factor} + {p99_margin_ms}ms",
            "outcome_parity": all(
                r["argmaxes"] == baseline_argmax
                for a in arms.values() for r in a),
            "passes": [{k: v for k, v in r.items() if k != "argmaxes"}
                       for a in arms.values() for r in a],
        },
        "rollout": {"good": good_run, "bad": bad_run},
        "degrade": {"control": control_burst, "treatment": treat_burst},
        "model": args.model,
        "devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
    }

    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, out_path)
    print(json.dumps({k: v for k, v in result.items()
                      if k not in ("shadow_impact", "rollout",
                                   "degrade")}))
    import shutil

    shutil.rmtree(trace_dir, ignore_errors=True)
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    if failures:
        sys.exit("fleet smoke FAILED:\n  - " + "\n  - ".join(failures)
                 + f"\n  see {out_path}")


def _silent_result(fut, timeout: float = 60.0):
    """Resolve a serve future to its logits or None (probe accounting —
    the probe's burst rides normal admission, so sheds are outcomes, not
    errors)."""
    try:
        return fut.result(timeout=timeout)
    except Exception:  # noqa: BLE001
        return None


def _smoke_model(args, vocab_size):
    """Mesh + sharded DP model + jitted step + put — the ONE model/mesh
    configuration every bench smoke measures against (``--pipeline``,
    ``--trace``, and ``--length`` all build on it, so they cannot drift in
    what they time).  Returns ``(mesh, cfg, tx, state0, sh, step, put)``."""
    from pdnlp_tpu.parallel import (
        make_global_batch, make_mesh, make_parallel_train_step,
        setup_sharded_model,
    )

    mesh = make_mesh(num_devices=args.num_devices, shape=args.mesh_shape)
    cfg, tx, state0, sh = setup_sharded_model(args, vocab_size, mesh, "dp")
    step = make_parallel_train_step(cfg, tx, args, mesh, sh)
    put = make_global_batch(mesh)
    return mesh, cfg, tx, state0, sh, step, put


def _smoke_train_setup(args):
    """Shared scaffold for the ``--pipeline`` and ``--trace`` smokes: the
    seeded corpus (real when present, synthetic otherwise), a
    fresh-DataLoader factory, and ONE jitted DP train step on the bench
    mesh (``_smoke_model``) — one copy, so the two smokes cannot drift in
    what they measure.  Returns ``(fresh_loader, mesh, state0, step, put)``."""
    import random

    from pdnlp_tpu.data import (
        Collator, DataLoader, WordPieceTokenizer, build_vocab,
    )
    from pdnlp_tpu.data.collate import EncodedDataset
    from pdnlp_tpu.data.sampler import DistributedShardSampler

    if os.path.exists(args.data_path):
        from pdnlp_tpu.data import load_data
        from pdnlp_tpu.data.tokenizer import get_or_build_vocab

        corpus = load_data(args.data_path)[:1024]
        tok = WordPieceTokenizer(get_or_build_vocab(args))
    else:
        chars = "天地人你我他好坏大小上下来去爱恨喜怒哀乐高兴悲伤讨厌愤怒"
        rng = random.Random(args.seed)
        corpus = [("".join(rng.choice(chars)
                           for _ in range(rng.randint(6, args.max_seq_len))),
                   rng.randrange(args.num_labels)) for _ in range(1010)]
        tok = WordPieceTokenizer(build_vocab((t for t, _ in corpus),
                                             size=256))

    def fresh_loader(encoded: bool = True):
        return DataLoader(
            corpus, Collator(tok, args.max_seq_len), args.train_batch_size,
            sampler=DistributedShardSampler(len(corpus), shuffle=True,
                                            seed=args.seed),
            prefetch=args.prefetch,
            encoded=EncodedDataset(corpus, tok, args.max_seq_len)
            if encoded else None)

    mesh, _cfg, _tx, state0, _sh, step, put = _smoke_model(
        args, tok.vocab_size)
    return fresh_loader, mesh, state0, step, put


def length_smoke(argv, modes_arg: str) -> None:
    """``--length {full,bucket,pack,all}``: length-aware training A/B.

    Short seeded training runs (bert-tiny, mesh DP, ``fuse_steps`` intact)
    per ``--length_mode``, all over ONE jitted step/multi-step pair, each
    driven through its own input pipeline (``auto`` — resident when
    eligible, exercising the per-bucket gathers).  The corpus is synthetic
    and CPU-safe with the REAL corpus's length shape (~18-token average,
    long tail) and a first-character-determined label, so every mode can
    actually learn it and the dev-accuracy parity gate compares converged
    numbers, not noise.  Reports per mode: samples/s and the speedup over
    ``full``, steps/epoch, compile counts (step + multi-step + resident
    gathers), the per-bucket batch histogram, token- and row-level padding
    waste, and dev accuracy on one SHARED full-width dev set (eval
    semantics never change with the training layout).  Exits non-zero on
    a retrace after warmup (any compile-cache growth during the timed
    epochs) or a dev-accuracy parity violation (``--length_tolerance``,
    default 0.08 absolute vs ``full``).  Writes ``results/
    length_smoke.json`` (override: ``--length_out``).
    """
    import random
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pdnlp_tpu.data import Collator, DataLoader, WordPieceTokenizer, build_vocab
    from pdnlp_tpu.data.collate import EncodedDataset
    from pdnlp_tpu.data.packing import PackedClassificationDataset
    from pdnlp_tpu.data.pipeline import build_pipeline
    from pdnlp_tpu.data.sampler import DistributedShardSampler
    from pdnlp_tpu.parallel import make_global_batch, make_parallel_eval_step
    from pdnlp_tpu.parallel.execution import make_parallel_multi_step
    from pdnlp_tpu.train.setup import build_length_train_loader
    from pdnlp_tpu.utils.config import Args, parse_cli, pop_cli_flag

    argv, out_path = pop_cli_flag(
        argv, "--length_out", os.path.join("results", "length_smoke.json"))
    argv, epochs = pop_cli_flag(argv, "--length_epochs", 6, int)
    argv, tolerance = pop_cli_flag(argv, "--length_tolerance", 0.08, float)
    # the smoke's bucket set adds a 16 floor under the stock 32/64/128:
    # this corpus (like the real one) averages ~18 tokens, so a 32-token
    # floor alone would pad the typical example ~45% — bucket choice is
    # part of the optimization, matched to the length profile
    args = parse_cli(argv, base=Args(
        model="bert-tiny", max_seq_len=128, train_batch_size=16,
        learning_rate=1e-3, dropout=0.0, attn_dropout=0.0, fuse_steps=4,
        length_buckets="16,32,64,128", log_every=10 ** 9))
    all_modes = ("full", "bucket", "pack")
    modes = all_modes if modes_arg == "all" else tuple(modes_arg.split(","))
    for m in modes:
        if m not in all_modes:
            sys.exit(f"--length {m!r}: pick from {'|'.join(all_modes)}|all")

    # synthetic corpus with the real corpus's length profile: one token per
    # CJK char, ~18-token average with a 30-126 tail; the label is a pure
    # function of the first character, so a converged dev accuracy is a
    # property of the MODE's training math, not of label noise
    chars = "天地人你我他好坏大小上下来去爱恨喜怒哀乐高兴悲伤讨厌愤怒"
    rng = random.Random(args.seed)

    def synth(n):
        out = []
        for _ in range(n):
            r = rng.random()
            length = (rng.randint(4, 24) if r < 0.78 else
                      rng.randint(25, 60) if r < 0.92 else
                      rng.randint(61, 126))
            text = "".join(rng.choice(chars) for _ in range(length))
            out.append((text, chars.index(text[0]) % args.num_labels))
        return out

    train_data, dev_data = synth(1024), synth(256)
    tok = WordPieceTokenizer(build_vocab((t for t, _ in train_data), size=256))
    col = Collator(tok, args.max_seq_len)
    enc = EncodedDataset(train_data, tok, args.max_seq_len)
    dev_enc = EncodedDataset(dev_data, tok, args.max_seq_len)
    dev_loader = DataLoader(
        dev_data, col, args.train_batch_size,
        sampler=DistributedShardSampler(len(dev_data), shuffle=False),
        encoded=dev_enc)

    mesh, cfg, tx, state0, sh, step, put = _smoke_model(args, tok.vocab_size)
    multi = make_parallel_multi_step(cfg, tx, args, mesh, sh)
    eval_step = make_parallel_eval_step(cfg, args, mesh, sh["params"])
    put_fused = make_global_batch(mesh, leading_stack=True)

    def cache_sizes(pipe):
        """(step, multi, gathers) compiled-variant counts — the bounded
        ``len(buckets) x len(step-variants)`` claim, measured."""
        gathers = sum(
            getattr(g, "_cache_size", lambda: 0)()
            for g in getattr(pipe, "_gathers", {}).values())
        return (step._cache_size(), multi._cache_size(), gathers)

    def run_epochs(pipe, state, n_epochs, first_epoch=0):
        """Dispatch ``n_epochs`` epochs; returns (state, examples, last).
        The caller fetches a VALUE from ``last`` before reading a clock —
        async dispatch would otherwise time enqueue, not compute."""
        examples, last = 0, None
        for e in range(first_epoch, first_epoch + n_epochs):
            pipe.set_epoch(e)
            for batch, n, fused, ex in pipe.macro_batches(args.fuse_steps):
                if fused:
                    state, m = multi(state, batch)
                    last = m["loss"][-1]
                else:
                    state, m = step(state, batch)
                    last = m["loss"]
                examples += ex
        return state, examples, last

    # compile the shared full-width eval program once up front: every mode
    # evaluates through the identical program, and the dev evals below all
    # run OUTSIDE the timed window
    ev = eval_step(state0["params"], put(next(iter(dev_loader))))
    float(jax.device_get(ev["correct"]))

    rows, acc_by_mode = [], {}
    for mode in modes:
        margs = args.replace(length_mode=mode)
        loader = build_length_train_loader(
            margs, train_data, col, enc,
            batch_size=args.train_batch_size)
        pipe = build_pipeline(margs, loader, put=put, put_fused=put_fused,
                              mesh=mesh)
        packed_stats = (loader.encoded.stats()
                        if isinstance(loader.encoded,
                                      PackedClassificationDataset) else None)
        # warmup: one full untimed epoch on a throwaway state copy visits
        # every (bucket x step-variant) shape this mode can produce.
        # step/multi jit caches are SHARED across the mode loop (that is
        # the point — one program pair), so per-mode compile counts are
        # deltas against the pre-warmup sizes, not absolute cache sizes
        pre = cache_sizes(pipe)
        wstate, _, wlast = run_epochs(
            pipe, jax.tree_util.tree_map(jnp.copy, state0), 1)
        float(jax.device_get(wlast))
        del wstate
        compiled = cache_sizes(pipe)
        pipe.stats.__init__()  # steady-state telemetry only
        pipe.stats.mode = pipe.mode

        state = jax.tree_util.tree_map(jnp.copy, state0)
        t0 = time.monotonic()
        state, examples, last = run_epochs(pipe, state, epochs,
                                           first_epoch=1)
        float(jax.device_get(last))  # completion barrier inside the timer
        elapsed = time.monotonic() - t0
        compiled_after = cache_sizes(pipe)
        retraces = sum(compiled_after) - sum(compiled)

        # dev accuracy, SHARED full-width eval path for every mode
        correct = weight = 0.0
        # untimed dev eval over a host loader: dispatch-all-then-gather is
        # already the async pattern, and the upload cost sits outside the
        # samples/s measurement window
        # jaxlint: disable=R7 — eval transport outside the timed window
        pending = [eval_step(state["params"], put(b)) for b in dev_loader]
        for m in jax.device_get(pending):
            correct += float(m["correct"])
            weight += float(m["weight"])
        acc = correct / max(weight, 1.0)
        acc_by_mode[mode] = acc
        del state

        snap = pipe.stats.snapshot()
        rows.append({
            "mode": mode,
            "pipeline": pipe.mode,
            "steps_per_epoch": len(loader),
            "epochs": epochs,
            "examples": examples,
            "samples_per_sec": round(examples / elapsed, 2),
            "steps_per_sec": round(snap["steps"] / elapsed, 2),
            "dev_accuracy": round(acc, 4),
            "compiled_variants": {
                "train_step": compiled[0] - pre[0],
                "multi_step": compiled[1] - pre[1],
                "resident_gathers": compiled[2] - pre[2]},
            "retraces_post_warmup": retraces,
            "padding_waste_tokens": snap["padding_waste_tokens"],
            "padding_waste_rows": snap["padding_waste_ratio"],
            "batches_by_bucket": {
                seq: b["steps"] for seq, b in
                snap.get("by_bucket", {}).items()},
            "by_bucket": snap.get("by_bucket"),
            "packing": packed_stats,
        })

    by_mode = {r["mode"]: r for r in rows}
    base_rate = by_mode.get("full", {}).get("samples_per_sec")
    for r in rows:
        r["speedup_vs_full"] = (round(r["samples_per_sec"] / base_rate, 3)
                                if base_rate and r["mode"] != "full"
                                else None)
    result = {
        "metric": "length_smoke",
        "model": args.model,
        "batch_size": args.train_batch_size,
        "seq_len": args.max_seq_len,
        "buckets": args.length_buckets,
        "fuse_steps": args.fuse_steps,
        "train_examples": len(train_data),
        "dev_examples": len(dev_data),
        "devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
        "dtype": args.dtype,
        "accuracy_tolerance": tolerance,
        "modes": rows,
    }
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, out_path)
    print(json.dumps({**result,
                      "modes": [{k: v for k, v in r.items()
                                 if k != "by_bucket"} for r in rows]}))
    bad_retrace = [r["mode"] for r in rows if r["retraces_post_warmup"]]
    if bad_retrace:
        sys.exit("length smoke FAILED: post-warmup retrace in "
                 f"{bad_retrace} — the compile count is not bounded by "
                 f"buckets x step-variants; see {out_path}")
    if "full" in acc_by_mode:
        drift = {m: round(a - acc_by_mode["full"], 4)
                 for m, a in acc_by_mode.items() if m != "full"}
        worst = [m for m, d in drift.items() if d < -tolerance]
        if worst:
            sys.exit("length smoke FAILED: dev-accuracy parity violated "
                     f"for {worst} (drift {drift}, tolerance {tolerance}) "
                     f"— see {out_path}")


def pipeline_smoke(argv, modes_arg: str) -> None:
    """``--pipeline {resident,prefetch,sync,all}``: input-pipeline A/B.

    Short seeded training runs (bert-tiny, mesh DP) through ONE shared
    jitted step, one run per pipeline mode, reporting steps/s and the
    transport counters (bytes uploaded per step, put-wait seconds,
    padding-waste ratio) — the numbers behind the device-resident claim:
    0 steady-state bytes/step at >= the sync pipeline's rate, with BITWISE
    identical per-step losses (enforced; a mismatch exits non-zero, as
    does any in-loop upload in resident mode).  ``resident`` is refused —
    loudly, with the reason recorded in the JSON — when the loader has no
    frozen ``EncodedDataset`` (a shuffling/augmenting collator re-encodes
    per epoch; there is nothing deterministic to hold in HBM).  Writes
    ``results/pipeline_smoke.json`` (override: ``--pipeline_out``); steps
    per mode: ``--pipeline_steps`` (default 30).  Deterministic and
    CPU-safe: a seeded synthetic corpus stands in when the real one is
    absent.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pdnlp_tpu.data.pipeline import build_pipeline
    from pdnlp_tpu.utils.config import Args, parse_cli, pop_cli_flag

    argv, out_path = pop_cli_flag(
        argv, "--pipeline_out", os.path.join("results", "pipeline_smoke.json"))
    # default covers one full epoch incl. the short final chunk, so the
    # padding-waste counter is exercised, not just defined
    argv, n_steps = pop_cli_flag(argv, "--pipeline_steps", 32, int)
    args = parse_cli(argv, base=Args(
        model="bert-tiny", max_seq_len=32, train_batch_size=32,
        learning_rate=1e-3, log_every=10 ** 9))
    all_modes = ("sync", "prefetch", "resident")
    modes = all_modes if modes_arg == "all" else tuple(modes_arg.split(","))
    for m in modes:
        if m not in all_modes:
            sys.exit(f"--pipeline {m!r}: pick from "
                     f"{'|'.join(all_modes)}|all")

    fresh_loader, mesh, state0, step, put = _smoke_train_setup(args)

    rows, losses = [], {}
    for mode in modes:
        loader = fresh_loader()
        pipe = build_pipeline(args.replace(pipeline=mode), loader, put=put,
                              mesh=mesh)
        # compile step + (resident) gather outside the timed window
        warm = pipe.warmup_batch(1)
        wstate, m = step(jax.tree_util.tree_map(jnp.copy, state0), warm)
        float(jax.device_get(m["loss"]))
        del wstate
        pipe.stats.__init__()  # drop warmup counts; keep steady-state only
        pipe.stats.mode = mode

        state = jax.tree_util.tree_map(jnp.copy, state0)
        seen, epoch = [], 0
        t0 = time.monotonic()
        while len(seen) < n_steps:
            pipe.set_epoch(epoch)
            for batch, n, _fused, _ex in pipe.macro_batches(1):
                state, m = step(state, batch)
                seen.append(m["loss"])
                if len(seen) == n_steps:
                    break
            epoch += 1
        losses[mode] = [float(x) for x in jax.device_get(seen)]
        elapsed = time.monotonic() - t0
        del state
        snap = pipe.stats.snapshot()
        rows.append({"mode": mode, "steps": n_steps,
                     "steps_per_sec": round(n_steps / elapsed, 2),
                     **{k: snap[k] for k in (
                         "bytes_per_step", "bytes_uploaded_in_loop",
                         "bytes_uploaded_total", "puts_in_loop",
                         "put_wait_sec", "padding_waste_ratio",
                         "prefetch_in_flight_max")}})

    # the refusal gate, demonstrated: no EncodedDataset -> no resident mode
    try:
        build_pipeline(args.replace(pipeline="resident"),
                       fresh_loader(encoded=False), put=put, mesh=mesh)
        refusal = None
    except ValueError as e:
        refusal = str(e)

    by_mode = {r["mode"]: r for r in rows}
    parity = None
    if "sync" in losses and "resident" in losses:
        parity = losses["sync"] == losses["resident"]
    result = {
        "metric": "pipeline_smoke",
        "model": args.model,
        "batch_size": args.train_batch_size,
        "seq_len": args.max_seq_len,
        "devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
        "dtype": args.dtype,
        "pipelines": rows,
        "loss_parity_bitwise": parity,
        "resident_vs_sync_speedup": round(
            by_mode["resident"]["steps_per_sec"]
            / by_mode["sync"]["steps_per_sec"], 3)
        if {"resident", "sync"} <= set(by_mode) else None,
        "resident_refusal_without_encoded": refusal,
    }
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, out_path)
    print(json.dumps(result))
    if "resident" in by_mode and \
            by_mode["resident"]["bytes_uploaded_in_loop"] != 0:
        sys.exit("pipeline smoke FAILED: resident mode uploaded "
                 f"{by_mode['resident']['bytes_uploaded_in_loop']} in-loop "
                 f"bytes (expected 0) — see {out_path}")
    if parity is False:
        sys.exit("pipeline smoke FAILED: resident losses diverge from sync "
                 f"— the gather is not bitwise faithful; see {out_path}")
    if refusal is None:
        sys.exit("pipeline smoke FAILED: resident mode accepted a loader "
                 "with no EncodedDataset (non-deterministic collation)")


def trace_smoke(argv) -> None:
    """``--trace``: obs tracing smoke — overhead gate + phase breakdown.

    Two short seeded training loops over ONE shared jitted step and
    warmed pipeline: untraced (a disabled ``obs.Tracer``, the exact no-op
    object production runs carry) vs traced (spans + per-step breakdown +
    regression detector).  Both variants run ``--trace_repeats`` times
    interleaved and keep their best rate — the honest comparison under CPU
    scheduler noise.  Reports steps/s for both, the overhead percentage,
    and the traced run's per-phase mean/p50/p95 breakdown embedded in the
    JSON; writes ``results/trace_smoke.json`` (override ``--trace_out``)
    plus the Chrome-trace export next to it, and EXITS NON-ZERO when the
    overhead exceeds ``--trace_tolerance`` (default 2%) or the export
    violates the Chrome-trace schema.  Deterministic and CPU-safe: the
    seeded synthetic corpus stands in when the real one is absent.
    """
    import time

    import jax
    import jax.numpy as jnp

    from pdnlp_tpu.data.pipeline import build_pipeline
    from pdnlp_tpu.obs import RegressionDetector, StepBreakdown, Tracer
    from pdnlp_tpu.obs.export import to_chrome_trace, write_chrome_trace
    from pdnlp_tpu.utils.config import Args, parse_cli, pop_cli_flag

    argv, out_path = pop_cli_flag(
        argv, "--trace_out", os.path.join("results", "trace_smoke.json"))
    argv, n_steps = pop_cli_flag(argv, "--trace_steps", 48, int)
    argv, repeats = pop_cli_flag(argv, "--trace_repeats", 3, int)
    argv, tolerance = pop_cli_flag(argv, "--trace_tolerance", 2.0, float)
    args = parse_cli(argv, base=Args(
        model="bert-tiny", max_seq_len=32, train_batch_size=32,
        learning_rate=1e-3, log_every=10 ** 9))

    fresh_loader, mesh, state0, step, put = _smoke_train_setup(args)

    # one pipeline per variant (the resident upload happens at build);
    # the traced pipeline's tracer is swapped per repeat below
    off = Tracer(enabled=False)
    pipes = {"untraced": build_pipeline(args, fresh_loader(), put=put,
                                        mesh=mesh, tracer=off),
             "traced": build_pipeline(args, fresh_loader(), put=put,
                                      mesh=mesh)}

    # compile the step + gather outside every timed window
    warm = pipes["untraced"].warmup_batch(1)
    wstate, m = step(jax.tree_util.tree_map(jnp.copy, state0), warm)
    float(jax.device_get(m["loss"]))
    del wstate, warm

    def timed_loop(pipe, tracer):
        """The traced-trainer loop shape: data_wait around the iterator,
        step_dispatch around the step, device_block on the loss.  With a
        disabled tracer every obs call is the production no-op, so the
        two variants differ ONLY by tracing overhead."""
        state = jax.tree_util.tree_map(jnp.copy, state0)
        seen, epoch, m = 0, 0, None
        t0 = time.monotonic()
        while seen < n_steps:
            pipe.set_epoch(epoch)
            for batch, n, _fused, _ex in tracer.wrap_iter(
                    "data_wait", pipe.macro_batches(1)):
                with tracer.span("step_dispatch", step=seen + 1, n=n):
                    state, m = step(state, batch)
                tracer.block(m["loss"], step=seen + 1, n=n)
                seen += 1
                if seen == n_steps:
                    break
            epoch += 1
        float(jax.device_get(m["loss"]))  # completion barrier, both runs
        dt = time.monotonic() - t0
        del state
        return n_steps / dt

    best = {"untraced": 0.0, "traced": 0.0}
    breakdown = detector = tracer = None
    for _ in range(max(1, repeats)):
        best["untraced"] = max(best["untraced"],
                               timed_loop(pipes["untraced"], off))
        tracer = Tracer(enabled=True)
        detector = RegressionDetector()
        breakdown = StepBreakdown(on_step=detector.observe)
        tracer.add_listener(breakdown.feed)
        pipes["traced"]._tracer = tracer
        best["traced"] = max(best["traced"],
                             timed_loop(pipes["traced"], tracer))
        breakdown.close()

    overhead_pct = (best["untraced"] / best["traced"] - 1.0) * 100
    records = tracer.records()
    chrome = to_chrome_trace(records)
    schema_ok = bool(chrome["traceEvents"]) and all(
        k in ev for ev in chrome["traceEvents"]
        for k in ("name", "ph", "ts", "pid", "tid"))
    trace_path = None
    if out_path:
        trace_path = out_path.rsplit(".", 1)[0] + ".trace.json"
        write_chrome_trace(records, trace_path)

    result = {
        "metric": "trace_smoke",
        "model": args.model,
        "batch_size": args.train_batch_size,
        "seq_len": args.max_seq_len,
        "steps": n_steps,
        "repeats": repeats,
        "pipeline": pipes["traced"].mode,
        "devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
        "dtype": args.dtype,
        "untraced_steps_per_sec": round(best["untraced"], 2),
        "traced_steps_per_sec": round(best["traced"], 2),
        "overhead_pct": round(overhead_pct, 2),
        "tolerance_pct": tolerance,
        "spans_recorded": len(records),
        "chrome_schema_ok": schema_ok,
        "chrome_export": trace_path,
        "regress_events": (detector.events if detector else []),
        "breakdown": breakdown.summary() if breakdown else None,
    }
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, out_path)
    print(json.dumps({k: v for k, v in result.items()
                      if k != "breakdown"}))
    if not schema_ok:
        sys.exit("trace smoke FAILED: Chrome-trace export is missing "
                 f"required event keys — see {trace_path}")
    if overhead_pct > tolerance:
        sys.exit(f"trace smoke FAILED: tracing costs {overhead_pct:.2f}% "
                 f"steps/s (tolerance {tolerance}%) — see {out_path}")


def telemetry_smoke(argv) -> None:
    """``--telemetry``: full-telemetry-plane overhead gate on the serve
    path.

    One closed-loop serve storm (DynamicBatcher over a bert-tiny engine,
    mixed-length synthesized requests) run twice, interleaved
    ``--telemetry_repeats`` times:

    - **OFF** — tracer disabled: no spans, no request hops, no memory
      sampling (the production default);
    - **ON** — the whole plane: span + per-request hop tracing, the
      per-batch HBM sampler, the live ``MetricsExporter`` (ephemeral-port
      ``/metrics`` + ``/healthz``) AND the flight-recorder JSONL at a
      2s cadence (5x the production 10s default).

    Throughput is estimated **per chunk, min over passes**: each arm's
    request stream is split into window-aligned chunks (drained at the
    boundary — batch formation stays deterministic, the bench asserts
    identical batch counts per arm) and each chunk keeps its FASTEST
    observation across the interleaved passes.  A shared-CI host's CPU
    steals are bursty; min-per-chunk filters them where a best-of over
    whole runs would need one entirely-clean 5-second window per arm —
    the same reason microbenchmarks report min, applied piecewise.

    Gates (non-zero exit): throughput delta <= ``--telemetry_tolerance``
    (default 1%), a NON-EMPTY ``/metrics`` scrape taken mid-storm (from a
    side thread — a dashboard polling must not need the storm to pause),
    at least one flight-recorder line on disk, and every ON-arm request's
    hop chain complete through the flushed span file (the
    ``trace_tpu.py request`` path).  Snapshot:
    ``results/telemetry_smoke.json``.  CPU-safe: the memory sampler
    no-ops where ``memory_stats`` is unsupported (recorded as
    ``memory.supported=false``).
    """
    import random
    import tempfile
    import threading
    import time
    import urllib.request
    from collections import deque

    import jax

    from pdnlp_tpu.data.tokenizer import WordPieceTokenizer, build_vocab
    from pdnlp_tpu.obs import MetricsExporter
    from pdnlp_tpu.obs.export import load_records
    from pdnlp_tpu.obs.request import validate_chains
    from pdnlp_tpu.obs.trace import Tracer
    from pdnlp_tpu.serve import DynamicBatcher, InferenceEngine
    from pdnlp_tpu.utils.config import Args, parse_cli, pop_cli_flag

    argv, n_requests = pop_cli_flag(argv, "--telemetry_requests", 1600,
                                    int)
    argv, repeats = pop_cli_flag(argv, "--telemetry_repeats", 8, int)
    argv, tolerance = pop_cli_flag(argv, "--telemetry_tolerance", 1.0,
                                   float)
    argv, out_path = pop_cli_flag(
        argv, "--telemetry_out",
        os.path.join("results", "telemetry_smoke.json"))
    args = parse_cli(argv, base=Args(model="bert-tiny"))

    chars = "天地人你我他好坏大小上下来去爱恨喜怒哀乐高兴悲伤讨厌愤怒"
    rng = random.Random(args.seed)
    lengths = [8, 14, 22, 30, 44, 58]
    texts = ["".join(rng.choice(chars)
                     for _ in range(lengths[i % len(lengths)]))
             for i in range(n_requests)]
    if os.path.exists(args.data_path) or os.path.exists(args.vocab_path):
        from pdnlp_tpu.data.tokenizer import get_or_build_vocab

        tok = WordPieceTokenizer(get_or_build_vocab(args))
    else:
        tok = WordPieceTokenizer(build_vocab(texts, size=256))

    # jaxlint: disable=L1 — flight recorder stays for post-run inspection
    td = tempfile.mkdtemp(prefix="pdnlp-telemetry-")
    # ONE tracer toggled per arm: the engine binds it at construction, and
    # flipping .enabled is exactly how production flips --trace
    tracer = Tracer(td, enabled=False, process_index=0)
    engine = InferenceEngine(args, tokenizer=tok, mesh=None, tracer=tracer)
    buckets = (32, 64)
    id_lists = [tok.encode_ids(t, max(buckets)) for t in texts]
    total_tokens = sum(len(i) for i in id_lists)
    flight_path = os.path.join(td, "flight.jsonl")
    chunk = 80  # window-aligned: every chunk drains to an empty batcher

    def run_arm(telemetry_on: bool) -> tuple:
        tracer.enabled = telemetry_on
        tracer.clear()
        exporter = None
        scrape: dict = {}
        scrape_thread = None
        batches0 = engine.metrics.batches_total.value
        if telemetry_on:
            exporter = MetricsExporter(
                {"serve": engine.metrics.snapshot,
                 "memory": engine.memory_snapshot},
                port=0, flight_path=flight_path,
                flight_interval_s=2.0).start()

        def scrape_now():
            try:
                base = f"http://127.0.0.1:{exporter.port}"
                with urllib.request.urlopen(base + "/metrics",
                                            timeout=10) as r:
                    scrape["metrics"] = r.read().decode()
                with urllib.request.urlopen(base + "/healthz",
                                            timeout=10) as r:
                    scrape["healthz"] = json.loads(r.read().decode())
            except Exception as e:  # noqa: BLE001 — recorded, gated below
                scrape["error"] = f"{type(e).__name__}: {e}"

        batcher = DynamicBatcher(engine, buckets=buckets, max_batch_size=8,
                                 max_wait_ms=2.0, max_queue=256,
                                 serve_pack="off").start()
        batcher.warmup()
        window = 2 * batcher.max_batch_size
        inflight: deque = deque()
        rids = []
        chunk_times = []
        t0 = time.monotonic()
        for i, ids in enumerate(id_lists):
            if telemetry_on and i == n_requests // 2:
                # mid-storm scrape from a side thread: the exporter must
                # serve a dashboard WHILE the storm runs, not around it
                scrape_thread = threading.Thread(target=scrape_now,
                                                 daemon=True)
                scrape_thread.start()
            fut = batcher.submit_ids(list(ids))
            rids.append(fut.rid)
            inflight.append(fut)
            while len(inflight) >= window:
                inflight.popleft().result(timeout=60)
            if (i + 1) % chunk == 0:
                while inflight:  # drain: chunk time owns its batches
                    inflight.popleft().result(timeout=60)
                t1 = time.monotonic()
                chunk_times.append(t1 - t0)
                t0 = t1
        while inflight:
            inflight.popleft().result(timeout=60)
        if n_requests % chunk:
            # a request count that is not a chunk multiple leaves a tail
            # whose tokens are counted — its time must be too
            chunk_times.append(time.monotonic() - t0)
        batcher.stop(drain=True)
        if scrape_thread is not None:
            scrape_thread.join(timeout=15)
        if exporter is not None:
            exporter.stop()
        batches = engine.metrics.batches_total.value - batches0
        return chunk_times, scrape, rids, batches

    best: dict = {"off": None, "on": None}
    # EVERY repeat's batch count (not just the last): the min-per-chunk
    # pool draws timings from all repeats, so any repeat that formed
    # different batches would poison the A/B
    batch_counts: dict = {"off": [], "on": []}
    per_repeat = []
    scrape: dict = {}
    rids: list = []
    for _ in range(max(1, repeats)):
        for mode in ("off", "on"):
            times, s, r_ids, batches = run_arm(mode == "on")
            batch_counts[mode].append(batches)
            if mode == "on":
                scrape, rids = s, r_ids
            best[mode] = times if best[mode] is None else \
                [min(a, b) for a, b in zip(best[mode], times)]
        per_repeat.append({
            m: round(total_tokens / sum(best[m]), 1) for m in best})
    off_tps = total_tokens / sum(best["off"])
    on_tps = total_tokens / sum(best["on"])
    overhead_pct = (off_tps / on_tps - 1.0) * 100

    # chain integrity of the LAST ON arm, through the file round trip
    trace_path = tracer.flush()
    chains = validate_chains(load_records(trace_path), rids)
    chains["incomplete"] = dict(list(chains["incomplete"].items())[:5])
    flight_lines = 0
    if os.path.exists(flight_path):
        with open(flight_path) as f:
            flight_lines = sum(1 for _ in f)
    memory = engine.memory_snapshot()

    result = {
        "metric": "telemetry_smoke",
        "model": args.model,
        "requests": n_requests,
        "real_tokens": total_tokens,
        "repeats": repeats,
        "buckets": list(buckets),
        "off_tokens_per_s": round(off_tps, 1),
        "on_tokens_per_s": round(on_tps, 1),
        "overhead_pct": round(overhead_pct, 2),
        "tolerance_pct": tolerance,
        "estimator": f"min-per-{chunk}-request-chunk over "
                     f"{repeats} interleaved passes",
        "batches_per_arm": batch_counts,
        "per_repeat_cumulative": per_repeat,
        "scrape": {
            "metrics_bytes": len(scrape.get("metrics", "")),
            "has_serve_counters": "pdnlp_serve_requests_total"
                                  in scrape.get("metrics", ""),
            "healthz": scrape.get("healthz"),
            "error": scrape.get("error"),
        },
        "flight_records": flight_lines,
        "request_tracing": chains,
        "memory": memory,
        "spans_recorded": len(tracer.records()),
        "devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
    }
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, out_path)
    print(json.dumps({k: v for k, v in result.items()
                      if k != "per_repeat_cumulative"}))

    failures = []
    if overhead_pct > tolerance:
        failures.append(
            f"telemetry plane costs {overhead_pct:.2f}% token throughput "
            f"(tolerance {tolerance}%): off {off_tps:.0f} vs on "
            f"{on_tps:.0f} tok/s")
    if batch_counts.get("off") != batch_counts.get("on"):
        failures.append(
            "batch formation diverged between arms "
            f"({batch_counts}) — the A/B is not comparing like work")
    if not result["scrape"]["has_serve_counters"]:
        failures.append(
            "mid-storm /metrics scrape missing serve counters "
            f"(bytes={result['scrape']['metrics_bytes']}, "
            f"error={result['scrape']['error']})")
    if flight_lines < 1:
        failures.append("flight recorder left no lines on disk")
    if chains["complete"] < chains["checked"]:
        failures.append(
            f"{chains['checked'] - chains['complete']} request(s) "
            f"without a complete hop chain ({chains['incomplete']})")
    if failures:
        sys.exit("telemetry smoke FAILED:\n  - "
                 + "\n  - ".join(failures) + f"\n  see {out_path}")


def kernel_smoke(argv) -> None:
    """``--kernels``: kernel-path parity + A/B smoke.

    Four gated blocks, written to ``results/kernel_smoke.json`` (override
    ``--kernels_out``), non-zero exit on any violation:

    1. **flash-attention parity** — pallas fwd/bwd vs XLA (dense mask AND
       segment-native packed mask), max |Δ| gated at fp32 tolerance;
    2. **no-HBM-bias proof** — the jaxpr of a packed ``bert.classify`` is
       walked recursively: under ``attn_impl=pallas`` NO equation may
       produce the [B, 1, S, S] ``segment_bias`` tensor (the XLA route
       must, as the sanity control) — materialization is checked
       structurally, not inferred from timings;
    3. **fused-CE parity** — kernel (loss, correct, objective) + grads vs
       the unfused logits path, and a full train step ``--fused_ce
       pallas`` vs ``xla`` at loss parity;
    4. **int8 serving** — a short seeded training run produces a real
       checkpoint; a bf16 and an int8 engine (the int8 one loading a
       ``quantize_ckpt``-style artifact) score the same dev set at
       dev-accuracy parity (``--kernels_tolerance``), zero post-warmup
       retraces each, with serve-forward throughput and the weight-bytes
       ratio recorded.  The >=1.5x int8 throughput gate applies on TPU,
       where the forward is weight-bound; on CPU the measured ratio is
       recorded (XLA CPU reads fp32-converted weights either way — there
       is no traffic to halve) and the gate is the parity set.

    Timings on a CPU host run the pallas kernels in INTERPRET mode (the
    ``pallas_interpreted`` flag in the JSON): numerics are identical to
    compiled Mosaic, speed is not — speedup columns are only meaningful
    from a TPU run, and the JSON says which kind produced it.
    """
    import random
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pdnlp_tpu.data import Collator, DataLoader, WordPieceTokenizer, build_vocab
    from pdnlp_tpu.data.collate import EncodedDataset
    from pdnlp_tpu.data.packing import segment_bias
    from pdnlp_tpu.data.sampler import DistributedShardSampler
    from pdnlp_tpu.models import bert, get_config
    from pdnlp_tpu.ops.attention import (
        dot_product_attention, mask_bias, resolve_impl, routed_impl,
    )
    from pdnlp_tpu.ops.fused_ce import fused_weighted_ce
    from pdnlp_tpu.serve import InferenceEngine
    from pdnlp_tpu.serve.offline import score_texts
    from pdnlp_tpu.serve.quant import quantize_params
    from pdnlp_tpu.train import checkpoint as ckpt_mod
    from pdnlp_tpu.train.steps import weighted_ce
    from pdnlp_tpu.utils.config import Args, parse_cli, pop_cli_flag

    argv, out_path = pop_cli_flag(
        argv, "--kernels_out", os.path.join("results", "kernel_smoke.json"))
    argv, epochs = pop_cli_flag(argv, "--kernels_epochs", 5, int)
    argv, tolerance = pop_cli_flag(argv, "--kernels_tolerance", 0.08, float)
    args = parse_cli(argv, base=Args(
        model="bert-tiny", max_seq_len=128, train_batch_size=16,
        learning_rate=1e-3, dropout=0.0, attn_dropout=0.0,
        log_every=10 ** 9))
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    failures = []

    def timeit_ms(fn, *a, reps=5):
        out = fn(*a)
        jax.block_until_ready(out)
        t0 = time.monotonic()
        for _ in range(reps):
            out = fn(*a)
        jax.block_until_ready(out)
        return (time.monotonic() - t0) / reps * 1e3

    # ---- 1. flash-attention parity (fwd + bwd), dense and segmented ----
    r = np.random.RandomState(args.seed)
    B, S, N, D = 2, args.max_seq_len, 4, 32
    q, k, v = (jnp.asarray(r.randn(B, S, N, D), jnp.float32)
               for _ in range(3))
    mask = jnp.asarray((r.rand(B, S) > 0.2).astype(np.int32)).at[:, 0].set(1)
    bias = mask_bias(mask)
    seg = np.zeros((B, S), np.int32)
    for b in range(B):
        pos = 0
        for sid in range(1, 5):
            ln = r.randint(8, S // 3)
            seg[b, pos:pos + ln] = sid
            pos += ln
            if pos >= S:
                break
    segj = jnp.asarray(seg)
    seg_bias = jnp.asarray(segment_bias(seg))

    def attn_loss(impl, seg_route):
        def f(q, k, v):
            if seg_route:
                o = dot_product_attention(
                    q, k, v, impl=impl,
                    segment_ids=segj if impl == "pallas" else None,
                    bias=None if impl == "pallas" else seg_bias)
            else:
                o = dot_product_attention(q, k, v, bias, impl=impl)
            return (o.astype(jnp.float32) ** 2).sum()
        return f

    parity, attn_ms = {}, {}
    for label, seg_route in (("dense", False), ("packed", True)):
        outs, grads = {}, {}
        for impl in ("xla", "pallas"):
            fn = jax.jit(jax.value_and_grad(attn_loss(impl, seg_route),
                                            argnums=(0, 1, 2)))
            (val, g) = fn(q, k, v)
            outs[impl], grads[impl] = val, g
            attn_ms[f"attn_{label}_{impl}"] = round(
                timeit_ms(fn, q, k, v, reps=3 if impl == "pallas"
                          and not on_tpu else 5), 3)
        fwd_d = abs(float(outs["pallas"]) - float(outs["xla"])) \
            / max(abs(float(outs["xla"])), 1.0)
        bwd_d = max(float(jnp.abs(a - b).max())
                    for a, b in zip(grads["xla"], grads["pallas"]))
        parity[f"attn_{label}"] = {"fwd_rel": round(fwd_d, 9),
                                   "bwd_max_abs": round(bwd_d, 9)}
        if fwd_d > 1e-5 or bwd_d > 5e-4:
            failures.append(f"attention {label} parity: fwd_rel={fwd_d:g} "
                            f"bwd_max={bwd_d:g}")

    # ---- 2. structural no-HBM-bias proof on the packed classify --------
    cfg_t = get_config("bert-tiny", vocab_size=120).replace(max_position=S)
    params_t = bert.init_params(jax.random.key(0), cfg_t)
    M = 4
    cls = np.zeros((B, M), np.int64)
    for b in range(B):
        for mseg in range(1, M + 1):
            idx = np.flatnonzero(seg[b] == mseg)
            cls[b, mseg - 1] = idx[0] if idx.size else 0
    pbatch = {
        "input_ids": jnp.asarray(r.randint(0, 120, (B, S)), jnp.int32),
        "token_type_ids": jnp.zeros((B, S), jnp.int32),
        "attention_mask": jnp.asarray((seg > 0).astype(np.int32)),
        "segment_ids": segj,
        "cls_positions": jnp.asarray(cls, jnp.int32),
        "label": jnp.zeros((B, M), jnp.int32),
        "example_weight": jnp.ones((B, M), jnp.float32),
    }

    def shapes_in(jaxpr, acc):
        for eqn in jaxpr.eqns:
            for ov in eqn.outvars:
                aval = getattr(ov, "aval", None)
                if aval is not None and getattr(aval, "shape", None):
                    acc.add(tuple(aval.shape))
            for p in eqn.params.values():
                for sub in (p if isinstance(p, (list, tuple)) else [p]):
                    inner = getattr(sub, "jaxpr", None)
                    if inner is not None:
                        shapes_in(inner, acc)
        return acc

    bias_shape = (B, 1, S, S)
    materialized = {}
    for impl in ("pallas", "xla"):
        jx = jax.make_jaxpr(
            lambda p, bt: bert.classify(p, cfg_t, bt, attn_impl=impl)
        )(params_t, pbatch)
        materialized[impl] = bias_shape in shapes_in(jx.jaxpr, set())
    if materialized["pallas"]:
        failures.append("packed pallas route materializes the "
                        f"{bias_shape} segment_bias in its jaxpr")
    if not materialized["xla"]:
        failures.append("sanity: the XLA fallback no longer materializes "
                        "segment_bias — the structural check lost its "
                        "control")

    # ---- 3. fused-CE parity + train-step A/B ---------------------------
    T, H, C = 96, 64, args.num_labels
    f32 = jnp.asarray(r.randn(T, H), jnp.float32)
    W = jnp.asarray(r.randn(H, C) * 0.1, jnp.float32)
    bW = jnp.asarray(r.randn(C) * 0.1, jnp.float32)
    lab = jnp.asarray(r.randint(0, C, T))
    wts = jnp.asarray((r.rand(T) > 0.2).astype(np.float32))

    def ce_obj(fused):
        def f(f32, W, bW):
            if fused:
                return fused_weighted_ce(f32, W, bW, lab, wts,
                                         smoothing=0.1)[2]
            return weighted_ce(f32 @ W + bW, lab, wts, smoothing=0.1)[2]
        return f

    ce_ms, ce_out = {}, {}
    for mode, fused in (("xla", False), ("pallas", True)):
        fn = jax.jit(jax.value_and_grad(ce_obj(fused), argnums=(0, 1, 2)))
        ce_out[mode] = fn(f32, W, bW)
        ce_ms[f"fused_ce_{mode}"] = round(timeit_ms(fn, f32, W, bW), 3)
    ce_val = abs(float(ce_out["pallas"][0]) - float(ce_out["xla"][0]))
    ce_grad = max(float(jnp.abs(a - b).max())
                  for a, b in zip(ce_out["xla"][1], ce_out["pallas"][1]))
    parity["fused_ce"] = {"value_abs": round(ce_val, 9),
                          "grad_max_abs": round(ce_grad, 9)}
    if ce_val > 1e-5 or ce_grad > 1e-4:
        failures.append(f"fused-CE parity: value={ce_val:g} "
                        f"grad_max={ce_grad:g}")

    # ---- 4. train a real checkpoint, then serve bf16 vs int8 -----------
    chars = "天地人你我他好坏大小上下来去爱恨喜怒哀乐高兴悲伤讨厌愤怒"
    rng = random.Random(args.seed)

    def synth(n):
        out = []
        for _ in range(n):
            ln = rng.randint(4, 24) if rng.random() < 0.8 \
                else rng.randint(25, 100)
            text = "".join(rng.choice(chars) for _ in range(ln))
            out.append((text, chars.index(text[0]) % args.num_labels))
        return out

    train_data, dev_data = synth(1024), synth(256)
    tok = WordPieceTokenizer(build_vocab((t for t, _ in train_data),
                                         size=256))
    mesh, cfg, tx, state0, sh, step, put = _smoke_model(args, tok.vocab_size)
    loader = DataLoader(
        train_data, Collator(tok, args.max_seq_len), args.train_batch_size,
        sampler=DistributedShardSampler(len(train_data), shuffle=True,
                                        seed=args.seed),
        encoded=EncodedDataset(train_data, tok, args.max_seq_len))
    state = state0
    for _ in range(epochs):
        # a one-shot seeded smoke train, outside every timed window; the
        # pipeline subsystem is not under test here
        for batch in loader:
            # jaxlint: disable=R7 — untimed checkpoint-producing loop
            state, m = step(state, put(batch))
    float(jax.device_get(m["loss"]))
    host_params = jax.device_get(state["params"])
    os.makedirs(args.output_dir, exist_ok=True)
    fpath = os.path.join(args.output_dir, "kernel-smoke-cls.msgpack")
    ckpt_mod.save_params(fpath, {"params": host_params})
    # the offline artifact (scripts/quantize_ckpt.py math, same module)
    from flax import serialization

    qpath = os.path.join(args.output_dir, "kernel-smoke-cls.int8.msgpack")
    qtmp = qpath + ".tmp"
    with open(qtmp, "wb") as fh:
        fh.write(serialization.to_bytes(quantize_params(host_params)))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(qtmp, qpath)

    dev_texts = [t for t, _ in dev_data]
    dev_labels = np.asarray([y for _, y in dev_data])
    serve_rows, serve = {}, []
    fixed_ids = [[2] + list(r.randint(5, tok.vocab_size - 1,
                                      r.randint(3, 30))) + [3]
                 for _ in range(64)]
    for mode, path in (("bf16", fpath), ("int8", qpath)):
        eng = InferenceEngine(args.replace(serve_dtype=mode),
                              tokenizer=tok, mesh=mesh)
        eng.load_checkpoint(path)
        preds, _ = score_texts(eng, dev_texts, buckets=(32, 64, 128),
                               batch_size=16)
        acc = float((np.asarray(preds) == dev_labels).mean())
        eng.infer_ids(fixed_ids, args.max_seq_len)  # warm the fixed shape
        warm_retraces = eng.metrics.retraces.value
        fwd_ms = timeit_ms(lambda: eng.infer_ids(fixed_ids,
                                                 args.max_seq_len), reps=10)
        retraces = eng.metrics.retraces.value - warm_retraces
        serve_rows[mode] = {"dev_accuracy": round(acc, 4),
                            "forward_ms_batch64": round(fwd_ms, 3),
                            "rows_per_sec": round(64 / (fwd_ms / 1e3), 1),
                            "retraces_post_warmup": retraces,
                            # the timed forward runs at max_seq_len; the
                            # bucketed accuracy pass routes per width
                            "attn_impl": eng.routed_attn(args.max_seq_len),
                            "attn_impl_by_seq": {
                                str(s): i for s, i
                                in sorted(eng.attn_impl_by_seq.items())},
                            "dtype": eng.dtype_label,
                            "checkpoint": path}
        serve.append(serve_rows[mode])
        if retraces:
            failures.append(f"serve {mode}: {retraces} post-warmup "
                            "retraces (expected 0)")
    acc_drift = serve_rows["int8"]["dev_accuracy"] \
        - serve_rows["bf16"]["dev_accuracy"]
    if acc_drift < -tolerance:
        failures.append(f"int8 dev accuracy {serve_rows['int8']['dev_accuracy']}"
                        f" vs bf16 {serve_rows['bf16']['dev_accuracy']} "
                        f"(drift {acc_drift:+.4f}, tolerance {tolerance})")
    int8_speedup = round(serve_rows["bf16"]["forward_ms_batch64"]
                         / serve_rows["int8"]["forward_ms_batch64"], 3)
    if on_tpu and int8_speedup < 1.5:
        failures.append(f"int8 serve speedup {int8_speedup} < 1.5x on TPU")

    # weight HBM traffic per forward: the roofline quantity int8 halves
    def dense_bytes(tree, per_elem):
        total = 0
        for node in jax.tree_util.tree_leaves_with_path(tree):
            path, leaf = node
            if path and getattr(path[-1], "key", None) == "kernel" \
                    and getattr(leaf, "ndim", 0) >= 2:
                total += leaf.size * per_elem
        return total

    bytes_bf16 = dense_bytes(host_params, 2)
    qtree = quantize_params(host_params)
    bytes_int8 = dense_bytes(qtree, 1) + sum(
        leaf.size * 4 for path, leaf in
        jax.tree_util.tree_leaves_with_path(qtree)
        if path and getattr(path[-1], "key", None) == "qscale")

    result = {
        "metric": "kernel_smoke",
        "model": args.model,
        "seq_len": S,
        "devices": jax.device_count(),
        "platform": platform,
        "pallas_interpreted": not on_tpu,
        "routing": {
            # the policy table (resolve_impl), independent of this host's
            # backend: packed batches default to the segment-native kernel
            # on TPU; plus what THIS run actually routed
            "auto_packed_tpu": resolve_impl("auto", segmented=True,
                                            backend="tpu"),
            "auto_dense_tpu": resolve_impl("auto", segmented=False,
                                           backend="tpu"),
            "auto_packed_here": routed_impl("auto", S, segmented=True),
            "dropout_forces": routed_impl("pallas", S, dropout=True),
        },
        "segment_bias_materialized": materialized,
        "parity": parity,
        "timings_ms": {**attn_ms, **ce_ms},
        "serve": serve,
        "int8_vs_bf16": {
            "dev_accuracy_drift": round(acc_drift, 4),
            "accuracy_tolerance": tolerance,
            "forward_speedup": int8_speedup,
            "speedup_gate": "enforced >=1.5x on tpu; recorded on cpu "
                            "(weight traffic is the TPU-side bound)",
            "weight_bytes_bf16": bytes_bf16,
            "weight_bytes_int8": bytes_int8,
            "weight_bytes_ratio": round(bytes_bf16 / bytes_int8, 3),
        },
        "train": {"epochs": epochs, "examples": epochs * len(train_data),
                  "final_loss": round(float(jax.device_get(m["loss"])), 4)},
    }
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, out_path)
    print(json.dumps(result))
    if failures:
        sys.exit("kernel smoke FAILED:\n  - " + "\n  - ".join(failures)
                 + f"\nsee {out_path}")


def longcontext_smoke(argv) -> None:
    """``--longcontext``: the long-context gate (ROADMAP item 3).

    Six gated blocks, written to ``results/longcontext_smoke.json``
    (override ``--longcontext_out``), non-zero exit on any violation:

    1. **multi-tile kernel parity** — pallas fwd+bwd vs the XLA oracle at
       EVERY supported width (``--longcontext_widths``, default
       128/256/512), dense mask AND segment-native packed, plus the
       measured tile-map sparsity (the block-sparse skip's live fraction);
    2. **structural no-HBM-bias proof** — the jaxpr of a packed
       ``bert.classify`` at 512 and 1024 carries NO [B, 1, S, S] tensor
       under the pallas route (the XLA route must, as the control);
    3. **packed multi-width train throughput at 512** — ``--length_mode
       pack`` with 128/256/512 buckets vs the padded-full baseline over
       the SAME jitted DP step: gates fill >= 0.85 (the padding-waste
       headroom of the acceptance bar) and real-token throughput >=
       0.6x the slot-advantage (fill ratio of the two layouts), with
       zero post-warmup retraces;
    4. **ring+packed parity** — the sequence-parallel packed train step
       (ring attention, segment IDs sharded along seq) vs the
       single-device packed step, same batch, loss parity over 2 steps
       (recorded-skip on a single-device host);
    5. **mixed long/short storm** — chunked prefill (long widths 512)
       interleaved with a packed short-query storm on the online batcher:
       gates the short p99 against a short-only control run, exact
       long-request parity with whole-request scoring, zero lost;
    6. **zero post-warmup retraces** across the storm (the serve compile
       cache is closed by warmup, long widths included).

    Summary rows merge into ``results/longcontext.json`` through
    ``scripts/bench_longcontext.merge_rows`` — historical v5e rows are
    never clobbered (error-free rows win over incoming ones).

    On a CPU host the pallas kernels run in INTERPRET mode (numerics
    identical, speed meaningless — the throughput gate compares packed
    vs padded under the SAME backend, so the ratio stays meaningful) and
    serve packing is forced on (``auto`` only packs on TPU).
    """
    import random
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pdnlp_tpu.data import Collator, DataLoader, WordPieceTokenizer, build_vocab
    from pdnlp_tpu.data.collate import EncodedDataset
    from pdnlp_tpu.data.packing import pack_id_lists, segment_bias, segment_cap
    from pdnlp_tpu.data.sampler import DistributedShardSampler
    from pdnlp_tpu.models import bert, get_config
    from pdnlp_tpu.ops import flash
    from pdnlp_tpu.ops.attention import (
        ROUTING_TABLE, dot_product_attention, mask_bias, routed_impl,
    )
    from pdnlp_tpu.serve import DynamicBatcher, InferenceEngine
    from pdnlp_tpu.train.setup import build_length_train_loader
    from pdnlp_tpu.utils.config import Args, parse_cli, pop_cli_flag

    argv, out_path = pop_cli_flag(
        argv, "--longcontext_out",
        os.path.join("results", "longcontext_smoke.json"))
    argv, widths_s = pop_cli_flag(argv, "--longcontext_widths", "128,256,512")
    argv, epochs = pop_cli_flag(argv, "--longcontext_epochs", 2, int)
    args = parse_cli(argv, base=Args(
        model="bert-tiny-long", max_seq_len=512, train_batch_size=8,
        learning_rate=1e-3, dropout=0.0, attn_dropout=0.0,
        length_buckets="128,256,512", log_every=10 ** 9))
    widths = tuple(int(w) for w in widths_s.split(",") if w.strip())
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    failures = []

    # ---- 1. multi-tile kernel parity at every width, dense + packed ----
    def packed_seg(B, S, seed):
        r = np.random.RandomState(seed)
        seg = np.zeros((B, S), np.int32)
        for b in range(B):
            pos, sid = 0, 0
            while pos < S - 24:
                ln = r.randint(8, 48)
                sid += 1
                seg[b, pos: pos + ln] = sid
                pos += ln
        return seg

    parity = {}
    Bk, N, D = 2, 2, 32
    for S in widths:
        if not flash.supported_seq(S):
            failures.append(f"width {S} does not tile the kernel blocks")
            continue
        r = np.random.RandomState(args.seed)
        q, k, v = (jnp.asarray(r.randn(Bk, S, N, D), jnp.float32)
                   for _ in range(3))
        seg = packed_seg(Bk, S, seed=S)
        segj = jnp.asarray(seg)
        seg_b = jnp.asarray(segment_bias(seg))
        mask = jnp.asarray((r.rand(Bk, S) > 0.4).astype(np.int32)
                           ).at[:, 0].set(1).at[-1, :].set(0)  # filler row
        bias = mask_bias(mask)
        cases = {
            "dense": (lambda q, k, v: dot_product_attention(
                q, k, v, bias, impl="xla"),
                lambda q, k, v: flash.flash_attention(q, k, v, bias=bias)),
            "packed": (lambda q, k, v: dot_product_attention(
                q, k, v, bias=seg_b, impl="xla"),
                lambda q, k, v: flash.flash_attention(
                    q, k, v, segment_ids=segj)),
        }
        row = {}
        for label, (ref_fn, ker_fn) in cases.items():
            def loss(f):
                return lambda q, k, v: (f(q, k, v).astype(jnp.float32)
                                        ** 2).sum()
            rv, rg = jax.jit(jax.value_and_grad(
                loss(ref_fn), argnums=(0, 1, 2)))(q, k, v)
            kv_, kg = jax.jit(jax.value_and_grad(
                loss(ker_fn), argnums=(0, 1, 2)))(q, k, v)
            fwd = abs(float(kv_) - float(rv)) / max(abs(float(rv)), 1.0)
            bwd = max(float(jnp.abs(a - b).max()) for a, b in zip(rg, kg))
            row[label] = {"fwd_rel": round(fwd, 9),
                          "bwd_max_abs": round(bwd, 9)}
            if fwd > 1e-5 or bwd > 5e-4:
                failures.append(f"width {S} {label} parity: fwd={fwd:g} "
                                f"bwd={bwd:g}")
        row["tile_map_live_fraction"] = round(float(np.asarray(
            flash.segment_block_map(segj)).mean()), 4)
        parity[str(S)] = row

    # ---- 2. structural no-HBM-bias proof at 512 and 1024 ---------------
    def shapes_in(jaxpr, acc):
        for eqn in jaxpr.eqns:
            for ov in eqn.outvars:
                aval = getattr(ov, "aval", None)
                if aval is not None and getattr(aval, "shape", None):
                    acc.add(tuple(aval.shape))
            for p in eqn.params.values():
                for sub in (p if isinstance(p, (list, tuple)) else [p]):
                    inner = getattr(sub, "jaxpr", None)
                    if inner is not None:
                        shapes_in(inner, acc)
        return acc

    structural = {}
    cfg_t = get_config("bert-tiny-long", vocab_size=160)
    params_t = bert.init_params(jax.random.key(0), cfg_t)
    r = np.random.RandomState(0)
    for S in (512, 1024):
        cap = segment_cap(S, 8)
        lists = [list(r.randint(5, 150, r.randint(10, 100)))
                 for _ in range(12)]
        pbatch, _ = pack_id_lists(lists, S, rows=2, max_segments=cap)
        pbatch = {k2: jnp.asarray(v2) for k2, v2 in pbatch.items()}
        bias_shape = (2, 1, S, S)
        got = {}
        for impl in ("pallas", "xla"):
            jx = jax.make_jaxpr(
                lambda p, bt, impl=impl: bert.classify(p, cfg_t, bt,
                                                       attn_impl=impl)
            )(params_t, pbatch)
            got[impl] = bias_shape in shapes_in(jx.jaxpr, set())
        structural[str(S)] = got
        if got["pallas"]:
            failures.append(f"packed pallas route materializes the "
                            f"{bias_shape} bias at width {S}")
        if not got["xla"]:
            failures.append(f"sanity: XLA control lost its {bias_shape} "
                            f"materialization at width {S}")

    # ---- 3. packed multi-width train throughput at 512 -----------------
    chars = "天地人你我他好坏大小上下来去爱恨喜怒哀乐高兴悲伤讨厌愤怒"
    rng = random.Random(args.seed)

    def synth(n):
        out = []
        for _ in range(n):
            p = rng.random()
            ln = (rng.randint(6, 120) if p < 0.7 else
                  rng.randint(121, 350) if p < 0.92 else
                  rng.randint(351, 500))
            text = "".join(rng.choice(chars) for _ in range(ln))
            out.append((text, chars.index(text[0]) % args.num_labels))
        return out

    train_data = synth(512)
    tok = WordPieceTokenizer(build_vocab((t for t, _ in train_data),
                                         size=256))
    col = Collator(tok, args.max_seq_len)
    enc = EncodedDataset(train_data, tok, args.max_seq_len)
    mesh, cfg, tx, state0, sh, step, put = _smoke_model(args, tok.vocab_size)

    train_rows = {}
    for mode in ("full", "pack"):
        margs = args.replace(length_mode=mode)
        loader = build_length_train_loader(margs, train_data, col, enc,
                                           batch_size=args.train_batch_size)
        state = jax.tree_util.tree_map(jnp.copy, state0)
        pre = step._cache_size()
        for batch in loader:  # warmup epoch: visit every shape, untimed
            # jaxlint: disable=R7 — untimed warmup outside the measured loop
            state, m = step(state, put(batch))
        float(jax.device_get(m["loss"]))
        compiled = step._cache_size() - pre
        real = slots = steps = 0
        t0 = time.monotonic()
        for _ in range(epochs):
            for batch in loader:
                # the transport IS part of the measured tokens/s here and
                # both modes pay it identically
                # jaxlint: disable=R7 — transport is inside the metric
                state, m = step(state, put(batch))
                real += int(batch["attention_mask"].sum())
                slots += int(batch["attention_mask"].size)
                steps += 1
        float(jax.device_get(m["loss"]))
        elapsed = time.monotonic() - t0
        retraces = step._cache_size() - pre - compiled
        train_rows[mode] = {
            "steps": steps, "compiled_variants": compiled,
            "retraces_post_warmup": retraces,
            "fill_ratio": round(real / slots, 4),
            "tokens_real_per_sec": round(real / elapsed, 1),
            "tokens_slot_per_sec": round(slots / elapsed, 1),
            "attn_impl_packed_512": routed_impl(
                args.attention_impl, 512, segmented=(mode == "pack")),
        }
        if retraces:
            failures.append(f"train {mode}: {retraces} post-warmup "
                            "retraces")
    fill_packed = train_rows["pack"]["fill_ratio"]
    fill_full = train_rows["full"]["fill_ratio"]
    ratio = (train_rows["pack"]["tokens_real_per_sec"]
             / max(train_rows["full"]["tokens_real_per_sec"], 1e-9))
    headroom = fill_packed / max(fill_full, 1e-9)
    train_rows["pack"]["real_token_speedup_vs_full"] = round(ratio, 3)
    train_rows["pack"]["slot_advantage"] = round(headroom, 3)
    if fill_packed < 0.85:
        failures.append(f"packed fill {fill_packed} < 0.85")
    if ratio < 0.6 * headroom:
        failures.append(f"packed real-token throughput {ratio:.2f}x < "
                        f"0.6 x slot advantage {headroom:.2f}")

    # ---- 4. ring+packed vs single-device packed parity -----------------
    ring = {"devices": jax.device_count()}
    if jax.device_count() >= 2:
        from jax.sharding import PartitionSpec  # noqa: F401
        from pdnlp_tpu.parallel import make_mesh
        from pdnlp_tpu.parallel.sp import make_sp_batch, make_sp_train_step
        from pdnlp_tpu.train.steps import make_train_step

        shape = ({"data": 2, "seq": 2} if jax.device_count() >= 4
                 else {"seq": 2})
        sp_mesh = make_mesh(shape=shape)
        sargs = args.replace(dtype="float32")
        scfg = get_config(args.model, vocab_size=tok.vocab_size,
                          num_labels=args.num_labels, dropout=0.0,
                          attn_dropout=0.0)
        sparams = bert.init_params(jax.random.key(1), scfg)
        from pdnlp_tpu.train.optim import build_optimizer
        from pdnlp_tpu.train.steps import init_state
        stx = build_optimizer(sparams, sargs)
        sstate = init_state(jax.random.key(1), scfg, stx, params=sparams)
        rb = np.random.RandomState(7)
        lists = [list(rb.randint(5, tok.vocab_size - 1, rb.randint(12, 90)))
                 for _ in range(24)]
        pb, _ = pack_id_lists(lists, 256, rows=4, max_segments=16)
        M = pb["cls_positions"].shape[1]
        pb["label"] = rb.randint(0, args.num_labels, (4, M)).astype(np.int32)
        pb["example_weight"] = (pb["cls_positions"] > 0).astype(np.float32)
        pb["example_weight"][:, 0] = 1.0
        put_sp = make_sp_batch(sp_mesh)
        sp_step = make_sp_train_step(scfg, stx, sargs, sp_mesh)(put_sp(pb))
        single = jax.jit(make_train_step(scfg, stx, sargs),
                         donate_argnums=0)
        s1 = jax.tree_util.tree_map(jnp.copy, sstate)
        s2 = jax.tree_util.tree_map(jnp.copy, sstate)
        diffs = []
        for _ in range(2):
            s1, m1 = sp_step(s1, put_sp(pb))
            s2, m2 = single(s2, {k2: jnp.asarray(v2)
                                 for k2, v2 in pb.items()})
            diffs.append(abs(float(m1["loss"]) - float(m2["loss"])))
        ring.update({"mesh": shape, "loss_max_abs_diff": max(diffs)})
        if max(diffs) > 2e-5:
            failures.append(f"ring+packed loss diverges from single-device "
                            f"packed by {max(diffs):g}")
    else:
        ring["skipped"] = "single-device host — parity pinned by " \
                          "tests/test_longcontext.py on the CPU mesh"

    # ---- 5/6. mixed long/short storm + retrace gate --------------------
    sargs = args.replace(max_seq_len=512)
    eng = InferenceEngine(sargs, tokenizer=tok)
    bat = DynamicBatcher(eng, buckets=(128,), max_batch_size=8,
                         max_wait_ms=8.0, max_queue=256,
                         serve_pack="on" if not on_tpu else "auto",
                         pack_max_segments=16,
                         long_widths=(256, 512)).start()
    bat.warmup()
    rs = np.random.RandomState(11)

    def short_ids():
        return [2] + list(rs.randint(5, tok.vocab_size - 1,
                                     rs.randint(4, 40))) + [3]

    def long_ids():
        return [2] + list(rs.randint(5, tok.vocab_size - 1,
                                     rs.randint(300, 480))) + [3]

    def storm(n_short, every_long):
        futs, longs = [], []
        lat = []
        for i in range(n_short):
            if every_long and i % every_long == 0:
                lf = bat.submit_ids(long_ids())
                longs.append(lf)
            futs.append((time.monotonic(), bat.submit_ids(short_ids())))
            time.sleep(0.002)
        for t0s, f in futs:
            f.result(timeout=60)
            lat.append((time.monotonic() - t0s) * 1e3)
        lres = [(f.ids, f.result(timeout=60)) for f in longs]
        return np.asarray(lat), lres

    warm_retraces = eng.metrics.retraces.value
    control, _ = storm(200, 0)
    mixed, long_results = storm(200, 10)
    storm_retraces = eng.metrics.retraces.value - warm_retraces
    p99_control = float(np.percentile(control, 99))
    p99_mixed = float(np.percentile(mixed, 99))
    budget = max(3 * p99_control, p99_control + 250.0)
    serve_row = {
        "short_p99_ms_control": round(p99_control, 1),
        "short_p99_ms_mixed": round(p99_mixed, 1),
        "short_p99_budget_ms": round(budget, 1),
        "long_requests": len(long_results),
        "retraces_in_storm": storm_retraces,
    }
    if p99_mixed > budget:
        failures.append(f"mixed-storm short p99 {p99_mixed:.0f}ms blows "
                        f"the {budget:.0f}ms budget (control "
                        f"{p99_control:.0f}ms)")
    if storm_retraces:
        failures.append(f"{storm_retraces} post-warmup retraces in the "
                        "storm (long widths must be closed by warmup)")
    # chunked-prefill parity: every long result == whole-request scoring
    worst = 0.0
    for ids, got in long_results:
        w = 256 if len(ids) <= 256 else 512
        ref = eng.infer_ids([list(ids)], w)[0]
        worst = max(worst, float(np.abs(got - ref).max()))
    serve_row["long_parity_max_abs"] = worst
    if worst > 2e-5:
        failures.append(f"chunked-prefill parity {worst:g} > 2e-5")
    bat.stop()

    result = {
        "metric": "longcontext_smoke",
        "model": args.model,
        "platform": platform,
        "pallas_interpreted": not on_tpu,
        "devices": jax.device_count(),
        "widths": list(widths),
        "routing_table": {f"{k[0]}{'_packed' if k[1] else '_dense'}": v
                          for k, v in sorted(ROUTING_TABLE.items())},
        "kernel_parity": parity,
        "segment_bias_materialized": structural,
        "train_512": train_rows,
        "ring_packed": ring,
        "mixed_storm": serve_row,
    }
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, out_path)
    # merge the summary rows into results/longcontext.json — history wins
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "scripts"))
    import bench_longcontext as blc

    # row names carry the PLATFORM: merge_rows is history-wins, so an
    # un-keyed name written by a CPU smoke would forever block the
    # documented on-chip re-measurement from landing — per-platform names
    # let the v5e run coexist with (not fight) the CI numbers
    smoke_rows = {
        f"smoke_pack512_train_{platform}": {
            **{k2: train_rows["pack"][k2] for k2 in
               ("fill_ratio", "tokens_real_per_sec",
                "real_token_speedup_vs_full")},
            "config": {"seq": 512, "source": "bench.py --longcontext",
                       "platform": platform,
                       "pallas_interpreted": not on_tpu}},
        f"smoke_mixed_storm_{platform}": {
            **serve_row,
            "config": {"source": "bench.py --longcontext",
                       "platform": platform}},
    }
    _, merged = blc.merge_rows(smoke_rows)
    print(json.dumps(result))
    print(f"[longcontext] merged rows into results/longcontext.json: "
          f"{merged}", file=sys.stderr)
    if failures:
        sys.exit("longcontext smoke FAILED:\n  - " + "\n  - ".join(failures)
                 + f"\nsee {out_path}")


def resilience_smoke(argv) -> None:
    """``--resilience``: preemption-grade training smoke.

    Two gated blocks, written to ``results/resilience_smoke.json``
    (override ``--resilience_out``), non-zero exit on any violation:

    1. **save-pause A/B** — a seeded bert-tiny step loop saving full train
       state every ``--resilience_save_every`` steps, once through the
       synchronous ``checkpoint.save_state`` and once through the async
       writer (snapshot-in-loop + background publish).  Records the
       step-loop pause per save (mean/p95/max ms) for both, the async
       drain time, and writer stats.  Gates: every published file passes
       manifest verification, and the async writer ran with at most one
       save in flight (structural: one writer thread; the recorded stats
       must agree).
    2. **kill injection** — a width-1 elastic gang (CPU backend, 4 virtual
       devices) SIGKILLed mid-epoch; the supervisor must restart it from
       the async-published snapshot.  Gates: **zero lost optimizer steps**
       (the final train line reports step N/N — every remaining step ran
       after the restart), exactly one restart, and **bounded recovery**
       (total wall under ``--resilience_recovery_s``, default 600).  Runs
       single-process so the smoke is honest on images whose jax cannot
       form cross-process CPU gangs (the eviction-at-reduced-width path is
       chaos-tested in ``tests/test_chaos.py`` where the backend allows).
    """
    import re
    import subprocess
    import tempfile
    import time as _time

    import jax
    import jax.numpy as jnp

    from pdnlp_tpu.train import checkpoint as ckpt
    from pdnlp_tpu.train.async_ckpt import AsyncCheckpointer
    from pdnlp_tpu.utils.config import Args, parse_cli, pop_cli_flag

    argv, out_path = pop_cli_flag(
        argv, "--resilience_out",
        os.path.join("results", "resilience_smoke.json"))
    argv, n_steps = pop_cli_flag(argv, "--resilience_steps", 18, int)
    argv, save_every = pop_cli_flag(argv, "--resilience_save_every", 3, int)
    argv, recovery_bound = pop_cli_flag(argv, "--resilience_recovery_s",
                                        600.0, float)
    if n_steps < save_every:
        sys.exit(f"--resilience_steps ({n_steps}) must be >= "
                 f"--resilience_save_every ({save_every}): the smoke needs "
                 "at least one save to measure")
    args = parse_cli(argv, base=Args(
        strategy="dp", model="bert-tiny", data_limit=600, max_seq_len=32,
        train_batch_size=8, dtype="float32", dropout=0.0, attn_dropout=0.0,
        epochs=1, log_every=10 ** 9))

    fresh_loader, mesh, state0, step, put = _smoke_train_setup(args)
    batch = put(next(iter(fresh_loader())))
    # jaxlint: disable=L1 — holds the kill-injection gang's ckpts for triage
    tmp_dir = tempfile.mkdtemp(prefix="resilience_")

    def timed_saves(variant):
        state = jax.tree_util.tree_map(jnp.copy, state0)
        path = os.path.join(tmp_dir, f"{variant}.msgpack")
        writer = AsyncCheckpointer() if variant == "async" else None
        pauses = []
        state, m = step(state, batch)  # compile outside the timed loop
        float(jax.device_get(m["loss"]))
        for i in range(n_steps):
            state, m = step(state, batch)
            if (i + 1) % save_every == 0:
                t0 = _time.perf_counter()
                if writer is None:
                    # the sync baseline IS the hazard being measured
                    # jaxlint: disable=R9 — A/B baseline for the async saver
                    ckpt.save_state(path, state, meta={"step": i + 1})
                else:
                    writer.submit(path, ckpt.snapshot(state),
                                  meta={"step": i + 1})
                # the STEP-LOOP PAUSE is the metric: sync saves block
                # internally (consolidate fetches), async deliberately
                # measures snapshot+enqueue only — no barrier wanted
                # jaxlint: disable=R4 — the unblocked pause IS the metric
                pauses.append(_time.perf_counter() - t0)
        float(jax.device_get(m["loss"]))
        drain_s = 0.0
        stats = writer_error = None
        if writer is not None:
            t0 = _time.perf_counter()
            try:
                writer.wait()  # host-side thread join, not device dispatch
            except RuntimeError as e:
                # a failed publish must surface as a GATED violation in the
                # JSON result, not an unhandled traceback
                writer_error = str(e.__cause__ or e)
            # jaxlint: disable=R4 — times the writer drain, no device work
            drain_s = _time.perf_counter() - t0
            stats = writer.stats()
        del state
        ok, reason = ckpt.verify(path)
        p = sorted(pauses)
        row = {"variant": variant, "saves": len(pauses),
               "pause_mean_ms": round(sum(p) / len(p) * 1e3, 3),
               "pause_p95_ms": round(p[int(0.95 * (len(p) - 1))] * 1e3, 3),
               "pause_max_ms": round(p[-1] * 1e3, 3),
               "drain_s": round(drain_s, 3),
               "manifest_ok": ok, "manifest_reason": reason}
        if stats is not None:
            row["writer"] = stats
        if writer_error is not None:
            row["writer_error"] = writer_error
        return row

    sync_row = timed_saves("sync")
    async_row = timed_saves("async")

    # ---- kill injection: width-1 elastic gang, SIGKILL mid-epoch --------
    kill_dir = os.path.join(tmp_dir, "gang")
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               PYTHONUNBUFFERED="1", PDNLP_SPAWN_PORT="12421",
               PDNLP_FAULT_STEP="5", PDNLP_FAULT_PROC="0",
               PDNLP_FAULT_KIND="sigkill")
    for k in ("COORDINATOR_ADDRESS", "PROCESS_ID"):
        env.pop(k, None)
    corpus = args.data_path
    if not os.path.exists(corpus):
        import random as _random

        corpus = os.path.join(tmp_dir, "corpus.json")
        rng = _random.Random(args.seed)
        chars = "天地人你我他好坏大小上下来去爱恨喜怒哀乐高兴悲伤讨厌愤怒"
        rows = [[" ".join(rng.choice(chars)
                          for _ in range(rng.randint(4, 30))),
                 rng.randrange(args.num_labels)] for _ in range(600)]
        with open(corpus, "w", encoding="utf-8") as f:
            json.dump(rows, f, ensure_ascii=False)
    t0 = _time.monotonic()
    timed_out = False
    try:
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "multi-tpu-spawn-cls.py"),
             "--num_processes", "1", "--elastic", "true", "--resume_every",
             "2", "--stall_timeout", "60", "--log_every", "1",
             "--output_dir", kill_dir, "--data_path", corpus,
             "--model", "bert-tiny", "--data_limit", "256", "--max_seq_len",
             "32", "--train_batch_size", "4", "--dtype", "float32",
             "--dropout", "0.0", "--attn_dropout", "0.0", "--epochs", "1"],
            capture_output=True, text=True, timeout=recovery_bound, env=env)
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        # the recovery-bound violation must be a GATED result, not a crash
        timed_out = True
        rc = -1
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) \
            else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) \
            else (e.stderr or "")
    # jaxlint: disable=R4 — wall-clock of a subprocess, no device dispatch
    wall_s = _time.monotonic() - t0
    restarts = len(re.findall(r"restart \d+/", err))
    steps_line = re.findall(r"step：(\d+)/(\d+)", out)
    final_step, total_step = (int(steps_line[-1][0]), int(steps_line[-1][1])) \
        if steps_line else (0, -1)
    kill_row = {
        "completed": rc == 0,
        "timed_out": timed_out,
        "restarts": restarts,
        "final_step": final_step, "total_step": total_step,
        "lost_optimizer_steps": total_step - final_step,
        "recovery_wall_s": round(wall_s, 1),
        "recovery_bound_s": recovery_bound,
    }

    violations = []
    for row in (sync_row, async_row):
        if not row["manifest_ok"]:
            violations.append(f"{row['variant']}: published checkpoint "
                              f"fails manifest validation "
                              f"({row['manifest_reason']})")
    if async_row.get("writer_error"):
        violations.append(f"async writer publish failed: "
                          f"{async_row['writer_error']}")
    if not kill_row["completed"]:
        violations.append("killed gang did not complete: "
                          + ("recovery bound hit"
                             if timed_out else f"rc {rc}")
                          + f"; {err[-500:]}")
    if kill_row["lost_optimizer_steps"] != 0:
        violations.append(f"lost optimizer steps: {kill_row}")
    if kill_row["restarts"] != 1:
        violations.append(f"expected exactly 1 restart, saw "
                          f"{kill_row['restarts']}")
    if wall_s > recovery_bound:
        violations.append(f"recovery {wall_s:.0f}s over bound "
                          f"{recovery_bound:.0f}s")

    result = {
        "metric": "resilience_smoke",
        "model": args.model,
        "batch_size": args.train_batch_size,
        "seq_len": args.max_seq_len,
        "devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
        "steps": n_steps, "save_every": save_every,
        "save_pause": {"sync": sync_row, "async": async_row},
        "kill_injection": kill_row,
        "violations": violations,
        "ok": not violations,
    }
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    if violations:
        sys.exit("resilience smoke FAILED: " + "; ".join(violations))


def _lint_gate() -> None:
    """Refuse to burn accelerator time on a tree that fails the jaxlint
    gate (tracing + concurrency suites vs the committed baseline) — the
    same shape as the leaked-PDNLP_GELU_TANH refusal: a smoke number
    measured on a tree carrying NEW hazards is unreproducible evidence.
    Pure-ast, no jax import: the check costs ~2s against smokes that run
    for minutes."""
    from pdnlp_tpu.analysis import analyze_paths, baseline, default_paths

    repo = os.path.dirname(os.path.abspath(__file__))
    base_path = os.path.join(repo, baseline.DEFAULT_BASELINE)
    if not os.path.exists(base_path):
        return  # no ratchet recorded: nothing to enforce against
    findings = analyze_paths(default_paths(repo), root=repo)
    new, _fixed = baseline.compare(findings, baseline.load(base_path))
    if new:
        lines = "\n".join(f"  {f.path}:{f.line}: {f.rule_id} {f.message}"
                          for f in new[:20])
        more = "" if len(new) <= 20 else f"\n  ... and {len(new) - 20} more"
        sys.exit(
            "bench.py: jaxlint gate FAILED — this tree carries NEW "
            "tracing/concurrency violations vs results/"
            "jaxlint_baseline.json:\n" + lines + more + "\n"
            "Fix them (or suppress with a reasoned `# jaxlint: disable=`) "
            "and re-run scripts/lint_gate.sh before benching.")


def main() -> None:
    argv = sys.argv[1:]
    if not any(a in ("--help", "-h") for a in argv):
        _lint_gate()  # usage lookups stay free; every real run is gated
    if "--resilience" in argv:
        # resilience smoke intercept (async-save pause A/B + kill
        # injection, results/resilience_smoke.json) — like --kernels, not
        # an Args knob
        argv.remove("--resilience")
        return resilience_smoke(argv)
    if "--telemetry" in argv:
        # full-telemetry-plane overhead gate (exporter + flight recorder +
        # memory sampler + request hops vs all-off) — an intercept like
        # --trace, results/telemetry_smoke.json
        argv.remove("--telemetry")
        return telemetry_smoke(argv)
    if "--trace" in argv:
        # like --pipeline: a bench smoke intercept, not the Args.trace
        # bool (a traced HEADLINE run is `--trace true` on the ordinary
        # entrypoints; the bench's own flag is the overhead gate).  The
        # Args-style boolean value is tolerated — `--trace true` runs the
        # smoke, `--trace false` is a no-op — so the README's flag shape
        # works on every entrypoint including this one.
        i = argv.index("--trace")
        argv.pop(i)
        enabled = True
        if i < len(argv) and argv[i].lower() in ("true", "false", "1", "0"):
            enabled = argv.pop(i).lower() in ("true", "1")
        if enabled:
            return trace_smoke(argv)
    if "--pipeline" in argv:
        from pdnlp_tpu.utils.config import pop_cli_flag

        argv, modes_arg = pop_cli_flag(argv, "--pipeline", "all")
        return pipeline_smoke(argv, modes_arg)
    if "--length" in argv:
        # like --pipeline: a bench smoke intercept, not Args.length_mode (a
        # length-aware HEADLINE run is `--length_mode bucket|pack` on the
        # ordinary entrypoints; the bench's own flag is the A/B smoke)
        from pdnlp_tpu.utils.config import pop_cli_flag

        argv, modes_arg = pop_cli_flag(argv, "--length", "all")
        return length_smoke(argv, modes_arg)
    if "--kernels" in argv:
        # kernel-path smoke intercept (parity + A/B, results/
        # kernel_smoke.json) — like --pipeline/--length, not an Args knob
        argv.remove("--kernels")
        return kernel_smoke(argv)
    if "--longcontext" in argv:
        # long-context gate (multi-tile kernel parity, structural no-bias
        # proof, packed-512 throughput, ring+packed parity, mixed-storm
        # p99 — results/longcontext_smoke.json); an intercept like
        # --kernels.  The ring leg needs >1 device: give the CPU host its
        # virtual mesh BEFORE jax initializes (no-op for TPU backends,
        # the flag only shapes the host platform).
        if "jax" not in sys.modules:
            os.environ.setdefault(
                "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        argv.remove("--longcontext")
        return longcontext_smoke(argv)
    if "--fleet" in argv:
        # multi-model fleet gate: shadow-impact control/treatment, canary
        # rollout advance + bad-canary auto-rollback, degrade-tier burst
        # (results/fleet_smoke.json) — an intercept like --replay
        argv.remove("--fleet")
        return fleet_smoke(argv)
    if "--replay" in argv:
        # trace-driven load replay: controller-vs-static across replayed
        # traffic shapes (results/replay_smoke.json) — an intercept like
        # --serve-load
        argv.remove("--replay")
        return replay_smoke(argv)
    if "--decode" in argv:
        # generative-decoding gate (sharded KV cache, prefill/decode
        # split, continuous batching, mid-storm kill —
        # results/decode_smoke.json); an intercept like --serve-load
        argv.remove("--decode")
        return decode_smoke(argv)
    if "--serve-load" in argv or "--serve_load" in argv:
        # closed-loop router SLO gate (results/serve_load_smoke.json):
        # Poisson storm + mid-storm replica kill + rolling swap + overload
        # burst over N replica engines — like --serve, an intercept
        for flag in ("--serve-load", "--serve_load"):
            if flag in argv:
                argv.remove(flag)
        return serve_load_smoke(argv)
    if "--serve" in argv:
        # No pretrain-cache key to fold a leaked PDNLP_GELU_TANH into here:
        # serving would silently run tanh forwards over an erf-trained
        # checkpoint and record mismatched parity numbers.  Refuse.
        if os.environ.get("PDNLP_GELU_TANH", "0") == "1":
            sys.exit("bench.py --serve: PDNLP_GELU_TANH is set — the global "
                     "activation override would serve tanh forwards over a "
                     "checkpoint trained with the configured activation. "
                     "Unset it (the override belongs to "
                     "scripts/profile_step.py's A/B subprocesses only).")
        argv.remove("--serve")
        return serve_smoke(argv)

    import jax

    jax.config.update("jax_compilation_cache_dir", "output/xla_cache")

    from pdnlp_tpu.train.run import build_parallel_trainer
    from pdnlp_tpu.utils.config import Args, parse_cli

    # Recipe (r5: batch-64 sweep in results/recipe_b64_sweep.json; the r4
    # b32 grid in results/ema_sweep.json): batch 64 amortizes the step's
    # fixed AdamW+EMA cost (+36% examples/s — ablation + XProf profile in
    # results/profile_r05.json); tanh GELU replaces the erf backward's VPU
    # transcendental chain (+7% step rate at b64, ~53% bf16 MFU) and its
    # end-to-end pretrain GAINS accuracy (3ep: 0.5887 vs erf's 0.5813);
    # ONE fine-tune epoch with the warmup->linear-decay schedule compressed
    # into it — the same 1-epoch protocol the reference's headline uses —
    # measured BEST in the tanh sweep: 0.5975 (6e-5) vs 0.5925/0.5938 at
    # the 5e-5/7e-5 half-steps, 0.5938 (4.5e-5), 0.5900-0.5950 (2ep),
    # 0.5887 (3ep); eval cadence 24 finds the same 0.5975 best (cadence
    # stays 48); trained head restored
    # (init_head), weight EMA at decay 0.99 (evaluated/checkpointed
    # weights are the Polyak average; 0.995 regresses to 0.5850), best-of
    # checkpointing with eval every 48 steps — 48, not the reference's 50,
    # so the cadence stays exact under fuse_steps=4 (trainer.py boundary
    # note).  fuse_steps=4 rides one dispatch per 4 optimizer steps over
    # the tunneled transport (multi_step docstring).  The pretrain cache is
    # keyed by activation (pretrained-tanh.msgpack vs pretrained.msgpack)
    # so --gelu erf reruns stay reproducible against the erf artifact the
    # per-strategy matrix protocol uses.
    args = parse_cli(base=Args(
        strategy="dp", dtype="bfloat16", fuse_steps=4, gelu="tanh",
        train_batch_size=64, learning_rate=6e-5,
        epochs=1, lr_schedule="warmup_linear", ema_decay=0.99,
        sft_epochs=5,        # measured best; --sft_epochs 0 = MLM-only warm start
        dev=True, eval_step=48,  # in-loop eval, keep best (reference ritual)
        log_every=10 ** 9,   # no per-step printing inside the timed loop
    ))

    # A leaked PDNLP_GELU_TANH (scripts/profile_step.py's A/B subprocess
    # override) force-enables tanh on EVERY forward regardless of --gelu,
    # while the pretrain cache below keys its artifact name on args.gelu —
    # a tanh trunk would silently land in the erf-named pretrained.msgpack
    # and corrupt the provenance the activation-keyed cache exists to
    # protect.  Fold the override into the key: the run IS tanh, so make
    # args.gelu (and with it the cache suffix, the recorded config, and
    # the warm-start artifact) say so.
    if os.environ.get("PDNLP_GELU_TANH", "0") == "1" and \
            (args.gelu or "erf") != "tanh":
        print("bench.py: PDNLP_GELU_TANH=1 leaked into this run — every "
              f"forward computes tanh GELU regardless of --gelu {args.gelu!r}"
              ". Folding it into the config: this run is keyed/cached as "
              "gelu=tanh (pretrained-tanh.msgpack).", file=sys.stderr)
        args = args.replace(gelu="tanh")

    with contextlib.redirect_stdout(sys.stderr):
        import numpy as np

        # cache keyed by activation: an erf-pretrained trunk silently warm-
        # starting a tanh fine-tune (or vice versa) measured fine (0.5813)
        # but would make the recipe's provenance depend on which run filled
        # the cache first
        sfx = "" if (args.gelu or "erf") == "erf" else f"-{args.gelu}"
        pretrain_ckpt = args.ckpt_path(f"pretrained{sfx}.msgpack")
        mlm_ckpt = args.ckpt_path(f"pretrained-mlm{sfx}.msgpack")
        explicit_init = bool(args.init_from)
        if not os.path.exists(pretrain_ckpt) and not args.init_from:
            # one-time in-repo pretraining (the "download weights" analog):
            # MLM over the packed corpus, then the supervised stage over the
            # ~30k labeled externals (sweep_sft.py measured 5 epochs best;
            # --sft_epochs 0 stops after the MLM phase)
            try:
                from pdnlp_tpu.train.pretrain import (
                    run_pretrain, run_supervised_stage,
                )

                # ema_decay is the FINE-TUNE recipe's knob: the pretrain
                # stages must not inherit it, or the regenerated artifact
                # would silently become sft-stage EMA weights and stop
                # reproducing the measured headline numbers
                if args.sft_epochs > 0:
                    if not os.path.exists(mlm_ckpt):
                        # a prior run's phase-1 artifact is reusable as-is:
                        # a supervised-stage failure must not cost the
                        # ~25-min MLM rerun on the next invocation
                        run_pretrain(args.replace(
                            strategy="pretrain", train_batch_size=64,
                            epochs=150, learning_rate=2e-4, mlm_prob=0.3,
                            dev=False, lr_schedule=None, ema_decay=0.0,
                            ckpt_name=f"pretrained-mlm{sfx}.msgpack"))
                    run_supervised_stage(args.replace(
                        strategy="sft", init_from=mlm_ckpt, init_head=False,
                        epochs=args.sft_epochs, learning_rate=args.sft_lr,
                        lr_schedule="warmup_linear", train_batch_size=32,
                        dev=False, ema_decay=0.0,
                        ckpt_name=f"pretrained{sfx}.msgpack"))
                else:
                    run_pretrain(args.replace(
                        strategy="pretrain", train_batch_size=64, epochs=150,
                        learning_rate=2e-4, mlm_prob=0.3, dev=False,
                        lr_schedule=None, ema_decay=0.0,
                        ckpt_name=f"pretrained{sfx}.msgpack"))
            except Exception as e:  # bench must still produce its JSON line
                print(f"pretrain stage failed ({type(e).__name__}: {e})",
                      file=sys.stderr)
        if not args.init_from:
            if os.path.exists(pretrain_ckpt):
                # MLM-only artifacts ('mlm' tree, no classifier) fail the
                # init_head load loudly; the retry ladder below drops to
                # trunk-only for them
                args = args.replace(init_from=pretrain_ckpt, init_head=True)
            elif os.path.exists(mlm_ckpt):
                # phase 2 failed but the MLM trunk survives: still a far
                # better warm start than from-scratch weights
                print(f"supervised stage unavailable; warm-starting from "
                      f"the MLM trunk {mlm_ckpt}", file=sys.stderr)
                args = args.replace(init_from=mlm_ckpt, init_head=False)
            else:
                print("no pretrain artifact; benching from-scratch weights",
                      file=sys.stderr)

        try:
            trainer, train_loader, dev_loader = build_parallel_trainer(args, mode="dp")
        except Exception as e:
            # an explicitly requested --init_from must fail loudly; only the
            # auto-selected cache falls back (e.g. a stale pretrained.msgpack
            # from a different --model must not kill the JSON line)
            if explicit_init or not args.init_from:
                raise
            retries = []
            if args.init_head:
                # an MLM-only cache has no trained classifier: still a
                # valid trunk warm-start
                retries.append((args.replace(init_head=False),
                                "retrying trunk-only"))
            retries.append((args.replace(init_from=None, init_head=False),
                            "benching from-scratch weights"))
            for cand, action in retries:
                print(f"init_from {args.init_from!r} failed "
                      f"({type(e).__name__}: {e}); {action}", file=sys.stderr)
                try:
                    args = cand
                    trainer, train_loader, dev_loader = \
                        build_parallel_trainer(args, mode="dp")
                    break
                except Exception as e2:
                    e = e2
            else:
                raise e
        # compile outside the timer (the reference times a warm CUDA context)
        host_batch = next(iter(train_loader))
        batch = trainer.put(host_batch)
        trainer.train_step.lower(trainer.state, batch).compile()
        # eval must lower against a DEV-loader batch: dev_batch_size differs
        # from the train batch, and a mismatched shape here would push the
        # real eval compile inside the timed loop on a cold XLA cache
        dev_batch = trainer.put(next(iter(dev_loader)))
        trainer.eval_step.lower(trainer.state["params"], dev_batch).compile()
        if trainer.multi_step is not None:
            stacked = {k: np.stack([v] * args.fuse_steps)
                       for k, v in host_batch.items()}
            trainer.multi_step.lower(
                trainer.state, trainer.put_fused(stacked)).compile()
        # hot-loop step time measured separately (30 re-fed steps): the
        # timed epoch below includes the in-loop dev evals (the reference's
        # protocol), so deriving steps/s from it would blur two metrics
        import time as _time

        import jax.numpy as jnp

        # probe on a copy: train_step donates its state argument, and the
        # real run below still needs trainer.state's buffers intact
        state = jax.tree_util.tree_map(jnp.copy, trainer.state)
        for _ in range(3):
            state, m = trainer.train_step(state, batch)
        float(jax.device_get(m["loss"]))
        t0 = _time.time()
        for _ in range(30):
            state, m = trainer.train_step(state, batch)
        float(jax.device_get(m["loss"]))
        sec_per_step = (_time.time() - t0) / 30
        del state, m

        total_minutes = trainer.train(train_loader, dev_loader)
        minutes = total_minutes / args.epochs
        # time-to-accuracy from the in-loop eval history: minutes until the
        # dev accuracy first reached the reference's 0.57, and until the
        # run's best — the numbers per-epoch framing hides
        to_target = next((e["minutes"] for e in trainer.eval_history
                          if e["accuracy"] >= 0.57), None)
        best_acc = max((e["accuracy"] for e in trainer.eval_history),
                       default=0.0)
        to_best = next((e["minutes"] for e in trainer.eval_history
                        if e["accuracy"] >= best_acc), None)
        # trainer adopted the best-of-epoch params at the end of train()
        loss, acc = trainer.dev(dev_loader)

        # MFU only means something against the matching peak: report it for
        # bf16 on a recognized TPU generation, null otherwise (fp32 runs at
        # a different MXU rate; CPU runs have no meaningful peak).
        mfu = None
        peak = bf16_peak(jax.devices()[0])
        if args.dtype == "bfloat16" and peak is not None:
            mfu = step_flops(trainer.cfg, args.train_batch_size,
                             args.max_seq_len) / sec_per_step / peak

    print(json.dumps({
        "metric": "total_train_minutes",
        "value": round(total_minutes, 4),
        "unit": "min",
        # TOTAL wall-clock vs the reference's total (its 1-epoch 0.6336):
        # the honest time-to-accuracy comparison, not per-epoch
        "vs_baseline": round(NORTH_STAR_MIN / total_minutes, 4),
        "baseline_min": NORTH_STAR_MIN,
        "single_gpu_baseline_min": SINGLE_GPU_MIN,
        "min_per_epoch": round(minutes, 4),
        "epochs": args.epochs,
        "minutes_to_0.57": round(to_target, 4) if to_target else None,
        "minutes_to_best": round(to_best, 4) if to_best else None,
        "dev_accuracy": round(acc, 4),
        "dev_loss": round(loss, 4),
        "steps_per_epoch": len(train_loader),
        "steps_per_sec": round(1.0 / sec_per_step, 2),
        "batch_size": args.train_batch_size,
        "mfu_pct": round(mfu * 100, 1) if mfu is not None else None,
        "devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
        "dtype": args.dtype,
        # the attention impl the hot loop actually routed to
        # (ops.attention.routed_impl — same decision the traced step and
        # the step_dispatch span attr resolve)
        "attn_impl": trainer._routed_attn(
            args.max_seq_len, args.length_mode == "pack"),
        "fuse_steps": args.fuse_steps,
        # input-pipeline mode + measured transport (utils.metrics
        # .TransportStats): resident mode must show 0 in-loop bytes/step
        "pipeline": trainer.pipeline.mode if trainer.pipeline else None,
        "transport": trainer.pipeline.stats.snapshot()
        if trainer.pipeline else None,
        "init_from": args.init_from,
        "note": ("fine-tuned from in-repo two-phase pretrain (MLM over the "
                 "40k-text corpus + supervised stage over the ~30k labeled "
                 "examples outside the protocol's [:10000] slice; no egress "
                 "— the reference's pretrained-checkpoint download is "
                 "rebuilt in-repo); reference dev acc target 0.57"
                 if args.init_from else
                 "from-scratch weights; reference dev acc 0.57 is from a "
                 "pretrained model"),
    }))


if __name__ == "__main__":
    main()
