#!/usr/bin/env python
"""trace_tpu.py — inspect, diff, merge, and convert ``pdnlp_tpu.obs``
traces.

Subcommands:

- ``summarize <trace>`` — the per-phase table (count / total / mean / p50
  / p95 / share) of one trace file; a merged multi-rank trace additionally
  prints per-rank lines (steps, traced wall, peak HBM);
- ``diff <base> <candidate>`` — per-phase mean deltas between two traces;
  exits **1** when any phase's mean grew beyond ``--threshold`` (default
  0.20 = 20%) — the CI guard: run a traced smoke on main and on a PR, diff
  the two files, and a phase regression fails the job with the phase named;
- ``merge <trace_proc0.jsonl> <trace_proc1.jsonl> ... -o merged.json`` —
  align per-process monotonic clocks (flush-time ``_clock_sync`` records,
  falling back to heartbeat beat payloads via ``--hb_dir``) and emit ONE
  Perfetto timeline with ``pid`` = rank; ``--jsonl`` keeps the span-log
  format instead (feedable back into ``summarize``/``diff``/``request``);
- ``request <id> <trace...>`` — the hop chain of one served request
  (minted at batcher/router admission): admission tier, queue, pack
  placement ``(row, slot)``, dispatch, hedge/requeue/re-pack, completion —
  with per-hop gap durations; exits 1 when the chain is missing or
  incomplete;
- ``decisions <trace...>`` — the serve control plane's decision-record
  chains (``pdnlp_tpu.obs.decision``): per actuation, the cause metrics,
  the knob's old -> new value, and the post-actuation evaluation-window
  outcome (kept / auto-reverted, with the signal delta); exits 1 on a
  malformed chain (an action without an outcome — an unexplained knob
  turn);
- ``export <trace> -o out.json`` — convert a compact JSONL span log to
  Chrome-trace JSON (load it at https://ui.perfetto.dev or
  ``chrome://tracing``).

Accepted inputs everywhere: the per-process ``trace_proc<i>.jsonl`` files
``Tracer.flush`` writes, or an already-exported Chrome-trace ``.json``.
Pure stdlib — runs on hosts without jax installed.

    python trace_tpu.py summarize output/trace/trace_proc0.jsonl
    python trace_tpu.py diff main.jsonl pr.jsonl --threshold 0.2
    python trace_tpu.py merge output/trace/trace_proc*.jsonl -o merged.json
    python trace_tpu.py request r12345-7 output/trace/trace_proc0.jsonl
    python trace_tpu.py decisions output/trace/trace_proc0.jsonl
    python trace_tpu.py export output/trace/trace_proc0.jsonl -o t.json
"""
from __future__ import annotations

import argparse
import json
import sys

from pdnlp_tpu.obs.decision import format_decisions, validate_decisions
from pdnlp_tpu.obs.export import (
    load_records, write_chrome_trace, write_jsonl,
)
from pdnlp_tpu.obs.merge import merge_traces
from pdnlp_tpu.obs.phases import StepBreakdown, format_table
from pdnlp_tpu.obs.regress import diff_breakdowns
from pdnlp_tpu.obs.request import chain_issues, format_chain, hop_chain


def _summary(path: str):
    return StepBreakdown.from_records(load_records(path)).summary()


def _load_many(paths, hb_dir=None):
    """One or many trace files -> one record stream (clock-aligned when
    several files merge; a file with no clock source gets the same loud
    warning ``merge`` prints — its spans sort on an incomparable clock)."""
    if len(paths) == 1:
        return load_records(paths[0])
    records, report = merge_traces(paths, hb_dir=hb_dir)
    if not report["aligned"]:
        print("WARNING: some files had no _clock_sync record or "
              "heartbeat (--hb_dir) — cross-file ordering is unreliable",
              file=sys.stderr)
    return records


def cmd_summarize(ns) -> int:
    summary = _summary(ns.trace)
    if ns.json:
        print(json.dumps(summary, indent=2))
    else:
        print(format_table(summary))
    return 0


def cmd_diff(ns) -> int:
    base, cand = _summary(ns.base), _summary(ns.candidate)
    diff = diff_breakdowns(base, cand, threshold=ns.threshold,
                           min_mean_sec=ns.min_mean_sec,
                           min_count=ns.min_count,
                           ckpt_save_budget=ns.ckpt_save_budget)
    if ns.json:
        print(json.dumps(diff, indent=2))
    else:
        header = (f"{'phase':<14} {'base_ms':>10} {'cand_ms':>10} "
                  f"{'delta':>8}")
        print(header)
        print("-" * len(header))
        for name, row in diff["phases"].items():
            am, bm, d = (row["base_mean_sec"], row["cand_mean_sec"],
                         row["delta_ratio"])
            mark = "  << REGRESSED" if row["regressed"] else ""
            print(f"{name:<14} "
                  f"{am * 1e3 if am else float('nan'):>10.3f} "
                  f"{bm * 1e3 if bm else float('nan'):>10.3f} "
                  f"{f'{d:+.1%}' if d is not None else 'n/a':>8}{mark}")
        budget = diff.get("ckpt_save_budget")
        if budget is not None:
            p95 = budget["cand_p95_sec"]
            shown = (f"{p95 * 1e3:.3f}ms" if p95 is not None
                     else "n/a (no saves in trace)")
            print(f"ckpt_save p95 {shown} vs budget "
                  f"{budget['budget_sec'] * 1e3:.3f}ms"
                  + ("  << OVER BUDGET" if budget["exceeded"] else ""))
        impls = diff.get("impls")
        if impls and impls["changed"]:
            # a phase delta alongside this line is attributable: the two
            # runs did not execute the same kernels/precision
            print(f"impl mix changed: base={impls['base']} "
                  f"cand={impls['cand']}")
    if diff["regressions"]:
        print(f"REGRESSION: phase(s) {', '.join(diff['regressions'])} mean "
              f"grew >= {ns.threshold:.0%} vs {ns.base}", file=sys.stderr)
        return 1
    return 0


def cmd_merge(ns) -> int:
    records, report = merge_traces(ns.traces, hb_dir=ns.hb_dir)
    out = ns.output or "merged.trace.json"
    if ns.jsonl:
        write_jsonl(records, out)
    else:
        write_chrome_trace(records, out)
    for f in report["files"]:
        off = (f"offset {f['offset_s']:+.6f}s via {f['clock_source']}"
               if f["offset_s"] is not None else "UNALIGNED (no clock "
               "source — offset 0 assumed)")
        print(f"rank {f['rank']}: {f['path']}  {off}")
    print(f"wrote {out} — {report['records']} spans over ranks "
          f"{report['ranks']}"
          + ("" if ns.jsonl else " (pid = rank; load it at "
             "https://ui.perfetto.dev)"))
    if not report["aligned"]:
        print("WARNING: some files had no _clock_sync record or heartbeat "
              "(--hb_dir) — their spans merged unaligned", file=sys.stderr)
    return 0


def cmd_request(ns) -> int:
    records = _load_many(ns.traces, hb_dir=ns.hb_dir)
    chain = hop_chain(records, ns.id)
    if ns.json:
        print(json.dumps({"request_id": ns.id, "hops": chain,
                          "issues": chain_issues(chain)}, indent=2))
    else:
        print(format_chain(chain, ns.id))
    return 0 if chain and not chain_issues(chain) else 1


def cmd_decisions(ns) -> int:
    records = _load_many(ns.traces, hb_dir=ns.hb_dir)
    report = validate_decisions(records)
    if ns.json:
        print(json.dumps(report, indent=2))
    else:
        print(format_decisions(records))
    return 0 if not report["incomplete"] else 1


def cmd_export(ns) -> int:
    out = ns.output or (ns.trace.rsplit(".", 1)[0] + ".chrome.json")
    write_chrome_trace(load_records(ns.trace), out)
    print(f"wrote {out} — load it at https://ui.perfetto.dev "
          "or chrome://tracing")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="trace_tpu.py",
        description="summarize / diff / export pdnlp_tpu.obs traces")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("summarize", help="per-phase table of one trace")
    s.add_argument("trace")
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_summarize)

    d = sub.add_parser("diff", help="per-phase delta; exit 1 on regression")
    d.add_argument("base")
    d.add_argument("candidate")
    d.add_argument("--threshold", type=float, default=0.2,
                   help="flag a phase whose mean grew >= this fraction "
                        "(default 0.2)")
    d.add_argument("--min_mean_sec", type=float, default=1e-6,
                   help="phases under this base mean are never flagged "
                        "(noise floor)")
    d.add_argument("--min_count", type=int, default=5,
                   help="phases with fewer observations than this in "
                        "either trace are never flagged (1-2 samples of "
                        "an amortized upload are noise, not a trend)")
    d.add_argument("--ckpt_save_budget", type=float, default=None,
                   help="absolute bound (seconds) on the CANDIDATE trace's "
                        "in-loop ckpt_save p95 — under the async "
                        "checkpointer the phase measures device->host "
                        "snapshot + enqueue only, so a p95 over budget "
                        "means serialization/disk crept back onto the "
                        "step loop; exit 1 when exceeded")
    d.add_argument("--json", action="store_true")
    d.set_defaults(fn=cmd_diff)

    m = sub.add_parser("merge", help="align + merge per-process traces "
                                     "into one Perfetto timeline "
                                     "(pid = rank)")
    m.add_argument("traces", nargs="+",
                   help="trace_proc<i>.jsonl files (rank from filename)")
    m.add_argument("-o", "--output", default=None,
                   help="output path (default merged.trace.json)")
    m.add_argument("--hb_dir", default=None,
                   help="heartbeat dir (watchdog beats carry the wall/"
                        "mono clock pair) — the alignment fallback when a "
                        "trace has no _clock_sync record")
    m.add_argument("--jsonl", action="store_true",
                   help="emit a span-log JSONL instead of Chrome-trace "
                        "JSON (summarize/diff/request consume it)")
    m.set_defaults(fn=cmd_merge)

    r = sub.add_parser("request", help="one request's hop chain with "
                                       "per-hop durations")
    r.add_argument("id", help="the request id (r<pid>-<n>)")
    r.add_argument("traces", nargs="+",
                   help="trace file(s); several are clock-aligned first")
    r.add_argument("--hb_dir", default=None)
    r.add_argument("--json", action="store_true")
    r.set_defaults(fn=cmd_request)

    c = sub.add_parser("decisions", help="control-plane decision chains "
                                         "(cause -> action -> outcome); "
                                         "exit 1 on a malformed chain")
    c.add_argument("traces", nargs="+",
                   help="trace file(s); several are clock-aligned first")
    c.add_argument("--hb_dir", default=None)
    c.add_argument("--json", action="store_true")
    c.set_defaults(fn=cmd_decisions)

    e = sub.add_parser("export", help="JSONL span log -> Chrome-trace JSON")
    e.add_argument("trace")
    e.add_argument("-o", "--output", default=None)
    e.set_defaults(fn=cmd_export)
    return p


def main(argv=None) -> int:
    ns = build_parser().parse_args(argv)
    return ns.fn(ns)


if __name__ == "__main__":
    sys.exit(main())
