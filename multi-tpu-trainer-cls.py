"""Training through the declarative ``AutoTrainer`` — the HF Trainer analog.

Capability twin of ``/root/reference/multi-gpu-transformers-cls.py``: declare
``TrainerArgs`` (step-based eval/save, bf16 instead of fp16, best-model
reload — the reference's exact knobs at ``:150-168``), call ``train()`` and
``evaluate()``, print the runtime metrics HF Trainer reports
(``train_runtime``/``train_samples_per_second``, ``script.ipynb`` cell 23).

    python multi-tpu-trainer-cls.py [--bf16 true] [--eval_steps 50]
"""
from pdnlp_tpu.train.auto import AutoTrainer, TrainerArgs
from pdnlp_tpu.utils.logging import rank0_print


def parse_trainer_args(argv=None) -> TrainerArgs:
    """Typed CLI over ``TrainerArgs`` via the shared dataclass-arg builder
    (``utils.config.add_dataclass_args`` — one Optional-unwrapping loop for
    the whole framework)."""
    import argparse

    from pdnlp_tpu.utils.config import add_dataclass_args

    p = argparse.ArgumentParser()
    add_dataclass_args(p, TrainerArgs)
    ns, _ = p.parse_known_args(argv)
    targs = TrainerArgs(**vars(ns))
    from pdnlp_tpu.utils.config import enable_compilation_cache

    enable_compilation_cache(targs.to_args())
    return targs


if __name__ == "__main__":
    trainer = AutoTrainer(parse_trainer_args())
    train_metrics = trainer.train()
    rank0_print({k: round(v, 4) for k, v in train_metrics.items()})
    eval_metrics = trainer.evaluate()
    rank0_print({k: round(v, 4) for k, v in eval_metrics.items()})
