"""Self-spawning multi-process launcher — the ``mp.spawn`` analog.

Capability twin of ``/root/reference/multi-gpu-distributed-mp-cls.py:361``:
one command forks ``--num_processes`` worker processes that rendezvous over
TCP (``init_method="tcp://localhost:12345"`` -> ``jax.distributed.initialize``
with a localhost coordinator) and run the same mesh-DP training as
``multi-tpu-jax-cls.py``.  The parent is only a process manager, exactly like
``mp.spawn``.

On a TPU pod each host instead runs one process (use multi-tpu-jax-cls.py
with ``--coordinator_address``); this single-command spawn flavor is for
multi-process runs on one machine and is exercised in CI on the CPU backend,
where each worker owns a slice of virtual devices.

    python multi-tpu-spawn-cls.py --num_processes 2
"""
from __future__ import annotations

import os
import subprocess
import sys

from pdnlp_tpu.train.run import run_parallel
from pdnlp_tpu.utils.config import Args, parse_cli

# the tcp://localhost:12345 analog (different port: CI safety); the env
# override lets concurrent/back-to-back gangs avoid a lingering listener
# from a previously killed gang
_PORT = int(os.environ.get("PDNLP_SPAWN_PORT", "12355"))


def _launch_gang(args, extra_argv, num_processes=None) -> list:
    width = num_processes if num_processes is not None \
        else (args.num_processes or 1)
    procs = []
    for pid in range(width):
        env = dict(os.environ)
        env.update(
            COORDINATOR_ADDRESS=f"localhost:{_PORT}",
            NUM_PROCESSES=str(width),
            PROCESS_ID=str(pid),
        )
        procs.append(subprocess.Popen(
            [sys.executable, __file__, *sys.argv[1:], *extra_argv], env=env))
    return procs


def spawn(args) -> int:
    """Fork ``num_processes`` copies of this script with PROCESS_ID set
    (the ``mp.spawn(main_worker, nprocs=N)`` analog).

    With ``--elastic true`` the parent becomes a degrade-don't-die gang
    supervisor (``parallel/watchdog.GangSupervisor`` — the capability the
    reference entirely lacks: a dead rank leaves its NCCL peers hung
    forever): workers heartbeat and snapshot full train state every
    ``--resume_every`` steps; if any child crashes or the stalest heartbeat
    exceeds ``--stall_timeout``, the parent kills the WHOLE gang (SPMD
    collectives cannot absorb a lone replacement rank), EVICTS ranks
    classified dead (``--elastic_shrink``, default on), and relaunches the
    survivors from the newest snapshot with capped exponential backoff and
    a restart budget.  A same-width restart is a bitwise continuation
    (resume restores params + Adam moments + step + RNG over the seeded
    data order, ``tests/test_resume.py``/``tests/test_elastic.py``); a
    reduced-width restart remaps the data position by epoch fraction and
    reshards state onto the surviving mesh (``tests/test_chaos.py``).
    """
    if not args.elastic:
        procs = _launch_gang(args, [])
        rc = 0
        for p in procs:
            rc = p.wait() or rc
        return rc

    import shutil

    from pdnlp_tpu.parallel.watchdog import GangSupervisor, heartbeat_dir
    from pdnlp_tpu.train import checkpoint as ckpt

    # A previous run's AUTO snapshot would make fresh workers "resume" at
    # its final step and train nothing — elastic state is per-run.  A
    # user-supplied --resume_from is the opposite intent (continue THAT
    # run) and is left strictly alone.
    if not args.resume_from or args.resume_from == "auto":
        ckpt.discard(args.resume_path())
        ckpt.discard(args.resume_path() + "-best")
        best_json = args.resume_path() + "-best.json"
        if os.path.exists(best_json):
            os.remove(best_json)
    shutil.rmtree(heartbeat_dir(args.output_dir), ignore_errors=True)

    worker_argv = ["--heartbeat_interval",
                   str(args.heartbeat_interval or 2.0),
                   "--resume_every", str(args.resume_every or 10)]
    if not args.resume_from:
        worker_argv += ["--resume_from", "auto"]

    def launch(width):
        # --num_processes last wins in argparse, and _launch_gang sets the
        # matching NUM_PROCESSES env — a shrunken gang rendezvouses at its
        # new world size and its workers rebuild mesh/loaders/shardings at
        # the surviving width (elastic-width resume remaps the rest)
        return _launch_gang(args, worker_argv + ["--num_processes",
                                                 str(width)],
                            num_processes=width)

    return GangSupervisor(
        launch, args.output_dir, args.num_processes or 1,
        stall_timeout=args.stall_timeout, max_restarts=args.max_restarts,
        shrink=args.elastic_shrink, min_processes=args.min_processes,
        backoff=args.restart_backoff, backoff_cap=args.restart_backoff_cap,
    ).run()


def main() -> int:
    args = parse_cli(base=Args(strategy="spawn"))
    already_child = os.environ.get("PROCESS_ID") is not None
    multi = bool(args.num_processes and args.num_processes > 1)
    # --elastic also supervises a WIDTH-1 gang: a single preemptible worker
    # still wants SIGKILL detection + restart-from-snapshot (and it is the
    # resume target a shrunken gang degrades to)
    if (multi or args.elastic) and not already_child \
            and args.process_id is None:
        return spawn(args)
    # --mode picks the sharding the gang executes: dp (default, the
    # mp.spawn analog), zero (fully-sharded state spanning the process
    # boundary — the reference's actual DeepSpeed deployment shape,
    # multi-gpu-deepspeed-cls.py:299-302), tp/ep, pp (stage axis across
    # processes), or sp (ring attention's seq axis across processes).
    # Cross-process execution of zero/pp/tp/sp is pinned by
    # tests/test_spawn.py.
    if args.mode == "pp":
        from pdnlp_tpu.train.run import run_pipeline

        run_pipeline(args)
    elif args.mode == "sp":
        from pdnlp_tpu.train.run import run_sp

        run_sp(args)
    else:
        run_parallel(args, mode=args.mode)
    return 0


if __name__ == "__main__":
    sys.exit(main())
