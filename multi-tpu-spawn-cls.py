"""Self-spawning multi-process launcher — the ``mp.spawn`` analog.

Capability twin of ``/root/reference/multi-gpu-distributed-mp-cls.py:361``:
one command forks ``--num_processes`` worker processes that rendezvous over
TCP (``init_method="tcp://localhost:12345"`` -> ``jax.distributed.initialize``
with a localhost coordinator) and run the same mesh-DP training as
``multi-tpu-jax-cls.py``.  The parent is only a process manager, exactly like
``mp.spawn``.

On a TPU pod each host instead runs one process (use multi-tpu-jax-cls.py
with ``--coordinator_address``); this single-command spawn flavor is for
multi-process runs on one machine and is exercised in CI on the CPU backend,
where each worker owns a slice of virtual devices.

    python multi-tpu-spawn-cls.py --num_processes 2
"""
from __future__ import annotations

import os
import subprocess
import sys

from pdnlp_tpu.train.run import run_parallel
from pdnlp_tpu.utils.config import Args, parse_cli

# the tcp://localhost:12345 analog (different port: CI safety); the env
# override lets concurrent/back-to-back gangs avoid a lingering listener
# from a previously killed gang
_PORT = int(os.environ.get("PDNLP_SPAWN_PORT", "12355"))


def _launch_gang(args, extra_argv) -> list:
    procs = []
    for pid in range(args.num_processes):
        env = dict(os.environ)
        env.update(
            COORDINATOR_ADDRESS=f"localhost:{_PORT}",
            NUM_PROCESSES=str(args.num_processes),
            PROCESS_ID=str(pid),
        )
        procs.append(subprocess.Popen(
            [sys.executable, __file__, *sys.argv[1:], *extra_argv], env=env))
    return procs


def spawn(args) -> int:
    """Fork ``num_processes`` copies of this script with PROCESS_ID set
    (the ``mp.spawn(main_worker, nprocs=N)`` analog).

    With ``--elastic true`` the parent is also a failure detector (the
    capability the reference entirely lacks — a dead rank leaves its NCCL
    peers hung forever): workers heartbeat and snapshot full train state
    every ``--resume_every`` steps; if any child crashes or the stalest
    heartbeat exceeds ``--stall_timeout``, the parent kills the WHOLE gang
    (SPMD collectives cannot absorb a lone replacement rank) and relaunches
    it from the newest snapshot — a bitwise continuation, since resume
    restores params + Adam moments + step + RNG and the data order is a
    seeded permutation (``tests/test_resume.py``).
    """
    if not args.elastic:
        procs = _launch_gang(args, [])
        rc = 0
        for p in procs:
            rc = p.wait() or rc
        return rc

    import shutil
    import time

    from pdnlp_tpu.parallel.watchdog import GangMonitor, heartbeat_dir

    # A previous run's AUTO snapshot would make fresh workers "resume" at
    # its final step and train nothing — elastic state is per-run.  A
    # user-supplied --resume_from is the opposite intent (continue THAT
    # run) and is left strictly alone.
    if not args.resume_from or args.resume_from == "auto":
        for stale in (args.resume_path(), args.resume_path() + "-best",
                      args.resume_path() + "-best.json"):
            if os.path.exists(stale):
                os.remove(stale)
    shutil.rmtree(heartbeat_dir(args.output_dir), ignore_errors=True)

    worker_argv = ["--heartbeat_interval",
                   str(args.heartbeat_interval or 2.0),
                   "--resume_every", str(args.resume_every or 10)]
    if not args.resume_from:
        worker_argv += ["--resume_from", "auto"]
    restarts = 0
    while True:
        procs = _launch_gang(args, worker_argv)
        mon = GangMonitor(procs, args.output_dir, args.num_processes,
                          stall_timeout=args.stall_timeout)
        verdict = None
        while verdict is None:
            time.sleep(0.2)
            verdict = mon.poll()
        if verdict["kind"] == "done":
            return 0
        mon.kill_gang()
        if restarts >= args.max_restarts:
            print(f"[elastic] giving up after {restarts} restarts: {verdict}",
                  file=sys.stderr)
            return 1
        restarts += 1
        print(f"[elastic] gang failure {verdict} — restart {restarts}/"
              f"{args.max_restarts} from latest snapshot", file=sys.stderr)


def main() -> int:
    args = parse_cli(base=Args(strategy="spawn"))
    already_child = os.environ.get("PROCESS_ID") is not None
    if args.num_processes and args.num_processes > 1 and not already_child \
            and args.process_id is None:
        return spawn(args)
    # --mode picks the sharding the gang executes: dp (default, the
    # mp.spawn analog), zero (fully-sharded state spanning the process
    # boundary — the reference's actual DeepSpeed deployment shape,
    # multi-gpu-deepspeed-cls.py:299-302), tp/ep, pp (stage axis across
    # processes), or sp (ring attention's seq axis across processes).
    # Cross-process execution of zero/pp/tp/sp is pinned by
    # tests/test_spawn.py.
    if args.mode == "pp":
        from pdnlp_tpu.train.run import run_pipeline

        run_pipeline(args)
    elif args.mode == "sp":
        from pdnlp_tpu.train.run import run_sp

        run_sp(args)
    else:
        run_parallel(args, mode=args.mode)
    return 0


if __name__ == "__main__":
    sys.exit(main())
