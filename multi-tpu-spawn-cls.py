"""Self-spawning multi-process launcher — the ``mp.spawn`` analog.

Capability twin of ``/root/reference/multi-gpu-distributed-mp-cls.py:361``:
one command forks ``--num_processes`` worker processes that rendezvous over
TCP (``init_method="tcp://localhost:12345"`` -> ``jax.distributed.initialize``
with a localhost coordinator) and run the same mesh-DP training as
``multi-tpu-jax-cls.py``.  The parent is only a process manager, exactly like
``mp.spawn``.

On a TPU pod each host instead runs one process (use multi-tpu-jax-cls.py
with ``--coordinator_address``); this single-command spawn flavor is for
multi-process runs on one machine and is exercised in CI on the CPU backend,
where each worker owns a slice of virtual devices.

    python multi-tpu-spawn-cls.py --num_processes 2
"""
from __future__ import annotations

import os
import subprocess
import sys

from pdnlp_tpu.train.run import run_parallel
from pdnlp_tpu.utils.config import Args, parse_cli

_PORT = 12355  # the tcp://localhost:12345 analog (different port: CI safety)


def spawn(args) -> int:
    """Fork ``num_processes`` copies of this script with PROCESS_ID set
    (the ``mp.spawn(main_worker, nprocs=N)`` analog)."""
    procs = []
    for pid in range(args.num_processes):
        env = dict(os.environ)
        env.update(
            COORDINATOR_ADDRESS=f"localhost:{_PORT}",
            NUM_PROCESSES=str(args.num_processes),
            PROCESS_ID=str(pid),
        )
        procs.append(subprocess.Popen([sys.executable, __file__, *sys.argv[1:]],
                                      env=env))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


def main() -> int:
    args = parse_cli(base=Args(strategy="spawn"))
    already_child = os.environ.get("PROCESS_ID") is not None
    if args.num_processes and args.num_processes > 1 and not already_child \
            and args.process_id is None:
        return spawn(args)
    run_parallel(args, mode="dp")
    return 0


if __name__ == "__main__":
    sys.exit(main())
