#!/usr/bin/env bash
# jaxlint gate — the documented pre-push step (and what bench.py's smokes
# re-check before burning accelerator time).
#
# Runs ALL suites (tracing R* + concurrency T* + lifecycle L*) over the
# repo's standard hazard surface, enforces the committed count-based baseline
# (results/jaxlint_baseline.json: new findings fail, fixed findings only
# ever loosen the gate), and always leaves a SARIF artifact at
# results/jaxlint.sarif for CI annotation / editor ingestion — findings
# that are new vs the baseline carry level=error in it, grandfathered
# ones level=note.
#
# Usage:
#   scripts/lint_gate.sh              # gate + artifact
#   scripts/lint_gate.sh --fix-hints  # extra args pass through to the
#                                     # human-readable enforcement run
set -uo pipefail
cd "$(dirname "$0")/.."

# the SARIF artifact is written regardless of the verdict (a failing CI
# run needs the annotations MORE than a passing one)
python lint_tpu.py --suite all --format sarif > results/jaxlint.sarif
sarif_status=$?
if [ $sarif_status -ge 2 ]; then
    echo "lint_gate: jaxlint could not run (exit $sarif_status)" >&2
    exit "$sarif_status"
fi

python lint_tpu.py --suite all "$@"
status=$?
if [ $status -ne 0 ]; then
    echo "lint_gate: FAILED — new findings vs results/jaxlint_baseline.json" >&2
    echo "lint_gate: SARIF artifact at results/jaxlint.sarif" >&2
    exit "$status"
fi
echo "lint_gate: clean (SARIF artifact at results/jaxlint.sarif)"
