#!/usr/bin/env python
"""Accuracy-vs-pretrain-compute sweep under the best-of-epoch protocol.

Positional args select rows by name under the exact-name rule
(``pdnlp_tpu.utils.sweeps``): ``p15-e150`` runs exactly that checkpoint;
``p30`` substring-selects the whole p30 family.
"""
import os
import re
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pdnlp_tpu.utils.sweeps import make_selected, parse_only  # noqa: E402

CKPTS = [
    ("p30-e50", "output/pretrained-e50.msgpack"),
    ("p30-e100", "output/pretrained-e100.msgpack"),
    ("p30-e150", "output/pretrained_p30.msgpack"),
    ("p15-e150", "output/pretrained_r150.msgpack"),
    ("p15-e300", "output/pretrained.msgpack"),
]


def main():
    grid = dict(CKPTS)
    selected = make_selected(parse_only(sys.argv[1:]), grid)
    for name, ckpt in CKPTS:
        if not selected(name) or not os.path.exists(ckpt):
            continue
        p = subprocess.run(
            [sys.executable, "multi-tpu-jax-cls.py", "--dtype", "bfloat16",
             "--init_from", ckpt, "--dev", "true", "--eval_step", "50",
             "--log_every", "1000000000", "--ckpt_name", "sweep-tmp.msgpack"],
            capture_output=True, text=True, timeout=600)
        best = re.findall(r"【best accuracy】 ([\d.]+)", p.stdout)
        final = re.findall(r"accuracy：([\d.]+)", p.stdout)
        print(f"{name:10s} best={best[-1] if best else 'FAIL'} "
              f"final_test={final[-1] if final else '?'}", flush=True)
        if not best:
            print(p.stdout[-1200:], p.stderr[-1200:])


if __name__ == "__main__":
    main()
