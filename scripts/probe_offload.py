#!/usr/bin/env python
"""Probe: optimizer state in pinned host memory (DeepSpeed cpu-offload
analog) — does XLA's TPU host-memory space work here, and at what cost?"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

jax.config.update("jax_compilation_cache_dir", "output/xla_cache")

from pdnlp_tpu.models import bert, get_config
from pdnlp_tpu.parallel import make_mesh
from pdnlp_tpu.train.optim import build_optimizer
from pdnlp_tpu.train.steps import build_train_step, init_state
from pdnlp_tpu.utils.config import Args

N = 30
B, S = 32, 128

args = Args(strategy="dp", dtype="bfloat16")
mesh = make_mesh()
cfg = get_config(args.model, vocab_size=6013, num_labels=6)
key = jax.random.PRNGKey(0)
params = bert.init_params(key, cfg)
tx = build_optimizer(params, args)
state = init_state(key, cfg, tx, rng=jax.random.key(0, impl="rbg"),
                   params=params)
batch = jax.device_put({
    "input_ids": jnp.ones((B, S), jnp.int32),
    "token_type_ids": jnp.zeros((B, S), jnp.int32),
    "attention_mask": jnp.ones((B, S), jnp.int32),
    "label": jnp.zeros((B,), jnp.int32),
    "example_weight": jnp.ones((B,), jnp.float32),
})

dev_sh = NamedSharding(mesh, P())
host_sh = NamedSharding(mesh, P(), memory_kind="pinned_host")


def shardings_of(state, opt_kind):
    def walk(tree, sh):
        return jax.tree_util.tree_map(lambda _: sh, tree)

    return {
        "params": walk(state["params"], dev_sh),
        "opt_state": walk(state["opt_state"], opt_kind),
        "step": dev_sh,
        "rng": dev_sh,
    }


def timeit(name, step, st):
    st, m = step(st, batch)
    float(jax.device_get(m["loss"]))
    t0 = time.time()
    for _ in range(N):
        st, m = step(st, batch)
    float(jax.device_get(m["loss"]))
    print(f"{name:28s}: {(time.time()-t0)/N*1e3:7.2f} ms/step")
    return st


import optax

from pdnlp_tpu.models import bert as bert_mod
from pdnlp_tpu.train.precision import resolve_dtype
from pdnlp_tpu.train.steps import weighted_ce


def build_offload_step():
    """Train step with explicit host<->device staging of optimizer state
    (the DeepSpeed cpu-offload pattern: moments live in host RAM)."""
    dtype = resolve_dtype(args.dtype)

    def loss_fn(params, batch, rng):
        logits = bert_mod.classify(params, cfg, batch, dtype=dtype,
                                   deterministic=False, rng=rng)
        return weighted_ce(logits, batch["label"], batch["example_weight"])[0]

    def step(state, batch):
        rng = jax.random.fold_in(state["rng"], state["step"])
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch, rng)
        opt_dev = jax.device_put(state["opt_state"], dev_sh)      # host->dev
        updates, opt_dev = tx.update(grads, opt_dev, state["params"])
        params = optax.apply_updates(state["params"], updates)
        opt_host = jax.device_put(opt_dev, host_sh)               # dev->host
        return ({"params": params, "opt_state": opt_host,
                 "step": state["step"] + 1, "rng": state["rng"]},
                {"loss": loss})

    return step


fn = build_train_step(cfg, tx, args)
for name, kind in (("opt state on device", dev_sh),
                   ("opt state in pinned host", host_sh)):
    try:
        sh = shardings_of(state, kind)
        # fresh buffers: device_put with an identical sharding aliases the
        # input, and the donating step below would delete the original
        st = jax.device_put(jax.tree_util.tree_map(jnp.copy, state), sh)
        body = fn if kind is dev_sh else build_offload_step()
        step = jax.jit(body, donate_argnums=0, in_shardings=(sh, dev_sh),
                       out_shardings=(sh, dev_sh))
        timeit(name, step, st)
    except Exception as e:
        print(f"{name}: FAILED {type(e).__name__}: {str(e)[:300]}")
