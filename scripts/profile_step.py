#!/usr/bin/env python
"""Ablation + XProf profile of the hot train step (VERDICT r4 item 2).

Measures steps/s for the bench config and one-knob ablations (EMA off,
dropout off, fused vs split QKV, eval forward), captures an XProf trace of
the base step, and parses the trace's op-level table into the top time
sinks.  Writes ``results/profile_r05.json``.

Run on the real chip:  python scripts/profile_step.py
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def probe(args_kw, env=None, steps=30, trace_dir=None):
    """Fresh-process probe: build trainer, compile, time `steps` re-fed
    steps.  A subprocess per variant keeps XLA/env state independent."""
    import subprocess

    payload = json.dumps({"args": args_kw, "steps": steps,
                          "trace_dir": trace_dir})
    code = (
        "import json,sys,time\n"
        "spec=json.loads(sys.argv[1])\n"
        "import jax, jax.numpy as jnp\n"
        "jax.config.update('jax_compilation_cache_dir','output/xla_cache')\n"
        "from pdnlp_tpu.train.run import build_parallel_trainer\n"
        "from pdnlp_tpu.utils.config import Args\n"
        "args=Args(**spec['args'])\n"
        "tr,tl,_=build_parallel_trainer(args,mode='dp')\n"
        "batch=tr.put(next(iter(tl)))\n"
        "state=jax.tree_util.tree_map(jnp.copy,tr.state)\n"
        "for _ in range(3): state,m=tr.train_step(state,batch)\n"
        "float(jax.device_get(m['loss']))\n"
        "td=spec['trace_dir']\n"
        "if td: jax.profiler.start_trace(td)\n"
        "t0=time.time()\n"
        "for _ in range(spec['steps']): state,m=tr.train_step(state,batch)\n"
        "float(jax.device_get(m['loss']))\n"
        "dt=time.time()-t0\n"
        "if td: jax.profiler.stop_trace()\n"
        "ev=tr.eval_step\n"
        "p=state['params']\n"
        "for _ in range(3): r=ev(p,batch)\n"
        "float(jax.device_get(r['loss_sum']))\n"
        "t0=time.time()\n"
        "for _ in range(spec['steps']): r=ev(p,batch)\n"
        "float(jax.device_get(r['loss_sum']))\n"
        "de=time.time()-t0\n"
        "print(json.dumps({'steps_per_sec':spec['steps']/dt,"
        "'eval_steps_per_sec':spec['steps']/de}))\n"
    )
    e = dict(os.environ)
    e.update(env or {})
    out = subprocess.run([sys.executable, "-c", code, payload], env=e,
                         capture_output=True, text=True, cwd=REPO)
    if out.returncode != 0:
        print(out.stderr[-3000:], file=sys.stderr)
        return None
    return json.loads(out.stdout.strip().splitlines()[-1])


def parse_trace(trace_dir, steps=30):
    """Aggregate the TPU "XLA Ops" track of the Chrome trace jax.profiler
    writes (``*.trace.json.gz``) into per-op-family time.  (The xplane.pb
    route needs a tensorboard_plugin_profile matching the installed TF —
    absent here; the Chrome trace carries the same device timeline.)"""
    import collections
    import glob
    import gzip
    import re
    import shutil

    paths = sorted(glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True))
    if not paths:
        return {"error": "no trace.json.gz produced"}
    try:
        d = json.load(gzip.open(paths[-1]))
        evs = d["traceEvents"]
        dev_pid = next((e["pid"] for e in evs
                        if e.get("ph") == "M" and e.get("name") == "process_name"
                        and "TPU" in e["args"].get("name", "")), None)
        tids = {e["tid"]: e["args"].get("name", "") for e in evs
                if e.get("ph") == "M" and e.get("name") == "thread_name"
                and e["pid"] == dev_pid}
        fam = collections.defaultdict(float)
        cnt = collections.Counter()
        for e in evs:
            if (e.get("ph") == "X" and e["pid"] == dev_pid
                    and tids.get(e["tid"]) == "XLA Ops"):
                name = re.sub(r"\.\d+$", "", e["name"])
                fam[name] += e.get("dur", 0)
                cnt[name] += 1
        tot = sum(fam.values()) or 1.0
        keep = os.path.join(REPO, "results", "xprof_base_step.trace.json.gz")
        shutil.copy(paths[-1], keep)
        return {
            "source": "results/xprof_base_step.trace.json.gz "
                      f"(jax.profiler, {steps}-step window, base step)",
            "device_ms_per_step": round(tot / (steps * 1e3), 2),
            "op_families": [
                {"family": n, "ms_per_step": round(v / (steps * 1e3), 3),
                 "pct": round(100 * v / tot, 1),
                 "events_per_step": cnt[n] // steps}
                for n, v in sorted(fam.items(), key=lambda x: -x[1])[:14]],
        }
    except Exception as e:  # parsing is best-effort; ablations are primary
        return {"error": f"{type(e).__name__}: {e}"}


def main():
    base = dict(strategy="dp", dtype="bfloat16", ema_decay=0.99,
                log_every=10 ** 9, init_from="output/pretrained.msgpack",
                init_head=True)
    trace_dir = os.path.join(REPO, "results", "xprof_r05")
    off = {"PDNLP_FUSE_QKV": "0"}
    variants = {
        "base_split_qkv": (base, off),
        "fused_qkv": (base, {"PDNLP_FUSE_QKV": "1"}),
        "no_ema": ({**base, "ema_decay": 0.0}, off),
        "no_dropout": ({**base, "dropout": 0.0, "attn_dropout": 0.0}, off),
        "no_ema_no_dropout": (
            {**base, "ema_decay": 0.0, "dropout": 0.0, "attn_dropout": 0.0},
            off),
        "fp32": ({**base, "dtype": "float32"}, off),
        "bf16_grads_direct": ({**base, "grads_dtype": "compute"}, off),
        "bf16_grads_unroll1": (
            {**base, "grads_dtype": "compute", "scan_unroll": 1}, off),
        "b64": ({**base, "train_batch_size": 64}, off),
        "b128": ({**base, "train_batch_size": 128}, off),
        # tanh-GELU A/B (PDNLP_GELU_TANH): prices the exact-erf backward the
        # trace attributes ~3.3 ms/step to; a different model, so measured
        # here rather than shipped (models/bert.py:_gelu)
        "gelu_tanh": (base, {**off, "PDNLP_GELU_TANH": "1"}),
        "gelu_tanh_b64": ({**base, "train_batch_size": 64},
                          {**off, "PDNLP_GELU_TANH": "1"}),
        "gelu_tanh_b128": ({**base, "train_batch_size": 128},
                           {**off, "PDNLP_GELU_TANH": "1"}),
    }
    if len(sys.argv) > 1:
        if len(sys.argv) != 3 or sys.argv[1] != "--only":
            sys.exit(f"usage: {sys.argv[0]} [--only name,name,...]  "
                     f"(variants: {', '.join(variants)})")
        only = set(sys.argv[2].split(","))
        unknown = only - set(variants)
        if unknown:
            sys.exit(f"unknown variant(s): {', '.join(sorted(unknown))}  "
                     f"(variants: {', '.join(variants)})")
        variants = {k: v for k, v in variants.items() if k in only}
    # merge onto any existing artifact: reruns refresh rows, never drop the
    # rows (and analysis) other files cite as evidence
    path = os.path.join(REPO, "results", "profile_r05.json")
    results = {}
    prior = {}
    if os.path.exists(path):
        prior = json.load(open(path))
        results.update(prior.get("variants", {}))
    for name, (kw, env) in variants.items():
        td = trace_dir if name == "base_split_qkv" else None
        r = probe(kw, env=env, trace_dir=td)
        if r is not None:  # a failed probe must not null out a measured
            results[name] = r  # row the README/analysis cite (merge invariant)
        print(f"{name}: {r}", file=sys.stderr)

    out = dict(prior)
    out.update({
        "device": None,
        "config": "bert-base b32 s128 bf16 (bench recipe, fuse_steps=1 probe)",
        "variants": results,
    })
    if "base_split_qkv" in variants:  # trace only re-captured on a full run
        out["trace"] = parse_trace(trace_dir)
    try:
        import jax

        out["device"] = jax.devices()[0].device_kind
    except Exception:
        pass
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps({k: v for k, v in results.items()}, indent=2))
    print(f"wrote {path}", file=sys.stderr)


if __name__ == "__main__":
    main()
