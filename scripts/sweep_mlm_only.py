#!/usr/bin/env python
"""MLM-only ceiling sweep — the machine-readable artifact behind the
README's accuracy table rows that warm-start from the UNLABELED-text-only
pretrain (no supervised stage).

The reference's 0.57 comes from externally pretrained weights
(~5.4B tokens); the in-repo MLM stage sees only the ~1.5M-token corpus.
This sweep fine-tunes the SAME MLM trunk (``output/pretrained-mlm.msgpack``,
150 epochs @ mask 0.30 — the measured plateau of the epochs/mask grid:
0.476-0.4875 across 50/100/150/300 epochs at masks 0.15/0.30) under a grid
of fine-tune recipes, and writes ``output/mlm_only_sweep.json``.  Whatever
the best cell says IS the measured MLM-only ceiling of this corpus.

    python scripts/sweep_mlm_only.py
"""
import json
import os
import re
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MLM = "output/pretrained-mlm.msgpack"
OUT = "output/mlm_only_sweep.json"

# (label, extra argv) — all rows: bf16, dp, warm start from the MLM trunk
GRID = [
    ("1ep-constLR (reference exact protocol)", ["--epochs", "1"]),
    ("2ep-warmup_linear (shipped recipe)",
     ["--epochs", "2", "--lr_schedule", "warmup_linear"]),
    ("3ep-warmup_linear",
     ["--epochs", "3", "--lr_schedule", "warmup_linear"]),
    ("5ep-warmup_linear",
     ["--epochs", "5", "--lr_schedule", "warmup_linear"]),
    ("3ep-warmup_linear-lr2e-5",
     ["--epochs", "3", "--lr_schedule", "warmup_linear",
      "--learning_rate", "2e-5"]),
]

RE_ACC = re.compile(r"accuracy：([\d.]+)")


def main() -> None:
    os.chdir(ROOT)
    if not os.path.exists(MLM):
        sys.exit(f"{MLM} missing — run pretrain-tpu.py (or bench.py) first")
    rows = {}
    for label, extra in GRID:
        argv = [sys.executable, "multi-tpu-jax-cls.py", "--dtype", "bfloat16",
                "--init_from", MLM, "--ckpt_name", "mlm-sweep-tmp.msgpack",
                "--log_every", "1000000", "--warmup_compile", "true", *extra]
        print(f"=== {label}", flush=True)
        t0 = time.time()
        p = subprocess.run(argv, capture_output=True, text=True, timeout=1800)
        out = p.stdout + p.stderr
        if p.returncode != 0:
            print(out[-2000:])
            rows[label] = {"error": p.returncode, "argv": argv[1:]}
            continue
        accs = RE_ACC.findall(out)
        rows[label] = {"accuracy": float(accs[-1]) if accs else None,
                       "wall_s": round(time.time() - t0, 1),
                       "argv": argv[1:]}
        print(f"    -> {rows[label]}", flush=True)
    best = max((r["accuracy"] for r in rows.values()
                if r.get("accuracy") is not None), default=None)
    artifact = {
        "meta": {"trunk": MLM,
                 "trunk_recipe": "150 epochs packed MLM, span mask 0.30 "
                                 "(plateau of the 50-300 epoch x mask "
                                 "0.15/0.30 grid: 0.476-0.4875 under the "
                                 "1-epoch protocol)",
                 "mlm_only_best": best,
                 "written_by": "scripts/sweep_mlm_only.py"},
        "rows": rows,
    }
    with open(OUT, "w") as f:
        json.dump(artifact, f, indent=2, ensure_ascii=False)
    print(f"\nwrote {OUT}; MLM-only best = {best}")


if __name__ == "__main__":
    main()
