#!/usr/bin/env python
"""Bench XLA vs Pallas flash attention on the real chip — fwd+bwd, bf16.

Two views:
  1. attention op alone at BERT-base head geometry across sequence lengths
     (tokens held ~constant so times are comparable);
  2. the full fused train step at seq 128 (the benchmark shape) and seq 512
     (the long-context shape), --attention_impl xla vs pallas.

    python scripts/bench_attention.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "output/xla_cache")

from pdnlp_tpu.models import bert, get_config
from pdnlp_tpu.ops.attention import dot_product_attention, mask_bias
from pdnlp_tpu.train.optim import build_optimizer
from pdnlp_tpu.train.steps import build_train_step, init_state
from pdnlp_tpu.utils.config import Args

N = 50
NHEADS, HDIM = 12, 64


def timeit(fn, *a):
    out = fn(*a)
    jax.block_until_ready(out)
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0]).astype(jnp.float32))
    t0 = time.time()
    for _ in range(N):
        out = fn(*a)
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0]).astype(jnp.float32))
    return (time.time() - t0) / N * 1e3


print("== attention op fwd+bwd (bf16, 12 heads x 64, ~131k tokens total) ==")
print(f"{'seq':>6} {'batch':>6} {'xla ms':>9} {'pallas ms':>10} {'speedup':>8}")
for S in (128, 256, 512, 1024, 2048):
    B = max(1, 4096 * 32 // (S))  # hold B*S ~ 131k tokens
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (B, S, NHEADS, HDIM), jnp.bfloat16)
               for i in range(3))
    bias = mask_bias(jnp.ones((B, S), jnp.int32), jnp.bfloat16)

    def loss(q, k, v, impl):
        return jnp.sum(dot_product_attention(q, k, v, bias, impl=impl)
                       .astype(jnp.float32))

    times = {}
    for impl in ("xla", "pallas"):
        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)), static_argnums=3)
        times[impl] = timeit(g, q, k, v, impl)
    print(f"{S:>6} {B:>6} {times['xla']:>9.2f} {times['pallas']:>10.2f} "
          f"{times['xla']/times['pallas']:>8.2f}x")

print("\n== full fused train step (bert-base, bf16, fwd+bwd+AdamW) ==")
print(f"{'seq':>6} {'batch':>6} {'xla ms':>9} {'pallas ms':>10} {'speedup':>8}")
for S, B in ((128, 32), (512, 8), (1024, 4)):
    # attn_dropout=0: training-time probability dropout forces the XLA path
    # (ops.attention), so a pallas-vs-xla step comparison needs it off
    cfg = get_config("bert-base", vocab_size=16000, num_labels=6,
                     max_position=max(512, S), attn_dropout=0.0)
    key = jax.random.PRNGKey(0)
    params = bert.init_params(key, cfg)
    batch = jax.device_put({
        "input_ids": jnp.ones((B, S), jnp.int32),
        "token_type_ids": jnp.zeros((B, S), jnp.int32),
        "attention_mask": jnp.ones((B, S), jnp.int32),
        "label": jnp.zeros((B,), jnp.int32),
        "example_weight": jnp.ones((B,), jnp.float32),
    })
    times = {}
    for impl in ("xla", "pallas"):
        args = Args(dtype="bfloat16", attention_impl=impl)
        tx = build_optimizer(params, args)
        state = init_state(key, cfg, tx, rng=jax.random.key(0, impl="rbg"),
                           params=params)
        step = jax.jit(build_train_step(cfg, tx, args))
        times[impl] = timeit(lambda: step(state, batch)[1]["loss"])
    print(f"{S:>6} {B:>6} {times['xla']:>9.2f} {times['pallas']:>10.2f} "
          f"{times['xla']/times['pallas']:>8.2f}x")
