#!/usr/bin/env python
"""Run every strategy entrypoint once and collect the per-strategy table —
the analog of the reference's headline README table (reference README.md:10-20)
and of its all-checkpoints test.py/predict.py ritual (test.py:85-94).

Each row fine-tunes from the in-repo pretrain checkpoint (the reference's
rows all start from pretrained hfl/chinese-bert-wwm-ext).  Writes
output/matrix.json and prints a markdown table.

    python scripts/run_matrix.py [--skip-pretrain-check]
"""
import json
import os
import re
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CKPT = "output/pretrained.msgpack"

# (name, argv, env overrides, expected checkpoint)
RUNS = [
    ("single", [sys.executable, "single-tpu-cls.py",
                "--init_from", CKPT, "--init_head", "true"], {}, "output/single-cls.msgpack"),
    ("dataparallel", [sys.executable, "multi-tpu-dataparallel-cls.py",
                      "--init_from", CKPT, "--init_head", "true"], {}, "output/dataparallel-cls.msgpack"),
    ("dp (DDP analog)", [sys.executable, "multi-tpu-jax-cls.py",
                         "--init_from", CKPT, "--init_head", "true"], {}, "output/dp-cls.msgpack"),
    ("amp (bf16)", [sys.executable, "multi-tpu-amp-cls.py",
                    "--init_from", CKPT, "--init_head", "true"], {}, "output/amp-cls.msgpack"),
    ("shardmap (Horovod analog)", [sys.executable, "multi-tpu-shardmap-cls.py",
                                   "--init_from", CKPT, "--init_head", "true"], {},
     "output/shardmap-cls.msgpack"),
    ("zero (ZeRO-3 analog)", [sys.executable, "multi-tpu-zero-cls.py",
                              "--init_from", CKPT, "--init_head", "true"], {}, "output/zero-cls.msgpack"),
    ("accelerate", [sys.executable, "multi-tpu-accelerate-cls.py",
                    "--init_from", CKPT, "--init_head", "true"], {}, "output/accelerate-cls.msgpack"),
    ("trainer (HF Trainer analog)", [sys.executable, "multi-tpu-trainer-cls.py",
                                     "--bf16", "true", "--init_from", CKPT, "--init_head", "true"], {},
     None),
    # the spawn launcher forks real processes; on the one-chip image it runs
    # on the CPU backend with 2 processes x 4 virtual devices (the same
    # configuration the spawn execution test pins).  bert-small from
    # scratch: a bert-base run crosses jax.distributed's shutdown-barrier
    # deadline while rank 0 gloo-allgathers the 365MB checkpoint, and the
    # bert-base pretrain ckpt cannot warm-start a small model anyway —
    # this row is execution evidence (loss parity is pinned by
    # tests/test_spawn.py), not an accuracy comparison.
    ("spawn 2-proc (CPU backend)",
     [sys.executable, "multi-tpu-spawn-cls.py", "--num_processes", "2",
      "--model", "bert-small", "--data_limit", "2000", "--ckpt_name",
      "spawn-cls.msgpack"],
     {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": "--xla_force_host_platform_device_count=4"},
     "output/spawn-cls.msgpack"),
    # tp / pp are multi-device-only strategies: on the one-chip image they
    # run on the virtual CPU mesh with bert-tiny as execution evidence
    # (parity with dp is pinned by tests/test_parallel.py)
    ("tp 4x2 data*model (CPU mesh)",
     [sys.executable, "multi-tpu-tp-cls.py", "--model", "bert-tiny",
      "--max_seq_len", "64", "--data_limit", "2000",
      "--mesh_shape", '{"data": 4, "model": 2}',
      "--log_every", "1000000", "--ckpt_name", "tp-cls.msgpack"],
     {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
     "output/tp-cls.msgpack"),
    ("pp 2-stage (CPU mesh)",
     [sys.executable, "multi-tpu-pp-cls.py", "--model", "bert-tiny",
      "--max_seq_len", "64", "--data_limit", "2000",
      "--mesh_shape", '{"stage": 2}', "--num_devices", "2",
      "--microbatches", "4",
      "--log_every", "1000000", "--ckpt_name", "pp-cls.msgpack"],
     {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
     "output/pp-cls.msgpack"),
]

RE_MIN = re.compile(r"耗时：([\d.]+)分钟")
RE_ACC = re.compile(r"accuracy：([\d.]+)")
RE_EVAL_ACC = re.compile(r"eval_accuracy ([\d.]+)")
RE_RUNTIME = re.compile(r"'train_runtime': ([\d.]+)")


def main() -> None:
    os.chdir(ROOT)
    if not os.path.exists(CKPT):
        sys.exit(f"{CKPT} missing — run pretrain-tpu.py first")
    results = {}
    for name, argv, env_over, ckpt_path in RUNS:
        env = dict(os.environ, **env_over)
        print(f"=== {name}: {' '.join(argv[1:])}", flush=True)
        try:
            p = subprocess.run(argv, env=env, capture_output=True, text=True,
                               timeout=3000)
        except subprocess.TimeoutExpired:
            print("    -> TIMEOUT", flush=True)
            results[name] = {"error": "timeout"}
            continue
        out = p.stdout + p.stderr
        if p.returncode != 0:
            print(out[-3000:])
            results[name] = {"error": p.returncode}
            continue
        minutes = RE_MIN.findall(out)
        accs = RE_ACC.findall(out)
        eval_accs = RE_EVAL_ACC.findall(out)
        runtime = RE_RUNTIME.findall(out)
        row = {
            "minutes": float(minutes[-1]) if minutes else (
                round(float(runtime[-1]) / 60, 4) if runtime else None),
            "accuracy": float(accs[-1]) if accs else (
                float(eval_accs[-1]) if eval_accs else None),
            "checkpoint": ckpt_path if ckpt_path and os.path.exists(ckpt_path)
            else ("missing!" if ckpt_path else "output/auto/checkpoint-*"),
        }
        results[name] = row
        print(f"    -> {row}", flush=True)
    with open("output/matrix.json", "w") as f:
        json.dump(results, f, indent=2, ensure_ascii=False)
    print("\n| Strategy | min/epoch (incl. compile) | dev accuracy |")
    print("|---|---|---|")
    for name, row in results.items():
        if "error" in row:
            print(f"| {name} | FAILED | — |")
        else:
            print(f"| {name} | {row['minutes']} | {row['accuracy']} |")


if __name__ == "__main__":
    main()
