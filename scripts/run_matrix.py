#!/usr/bin/env python
"""Run every strategy entrypoint once and collect the per-strategy table —
the analog of the reference's headline README table (reference README.md:10-20)
and of its all-checkpoints test.py/predict.py ritual (test.py:85-94).

Methodology (bench.py's, applied per row):
- every row fine-tunes bert-base from the in-repo two-phase pretrain
  checkpoint under the reference's 1-epoch constant-LR protocol (the
  reference's rows all start from pretrained hfl/chinese-bert-wwm-ext);
- ``--warmup_compile`` AOT-compiles the step programs BEFORE the timed
  epoch (the warm-CUDA-context analog), and the persistent
  ``output/xla_cache`` carries compiled programs across rows/reruns;
- ``--probe_steps 30`` measures each row's steady-state hot-loop rate on
  re-fed batches before the epoch — the controlled per-strategy speed
  metric, immune to the tunneled device transport's run-to-run RTT
  variance that the epoch wall-clock (one dispatch per step + loader) is
  exposed to.  Compare strategies on the probe column; read the epoch
  column as end-to-end evidence;
- rows that die on a transient tunnel error (``remote_compile``/
  ``read body``) are retried once.

Writes ONE artifact, ``output/matrix.json`` (meta + every row, including
each row's argv), and prints the README's markdown table from it — the
README numbers are traceable to this file by construction.

    python scripts/run_matrix.py [--only row1,row2] [--out output/matrix.json]
"""
import argparse
import json
import os
import re
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CKPT = "output/pretrained.msgpack"
PRETRAIN = ["--init_from", CKPT, "--init_head", "true"]
TIMED = ["--warmup_compile", "true", "--probe_steps", "30"]

CPU_ENV = {"JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}

# (name, argv, env overrides, expected checkpoint, note)
RUNS = [
    ("single", [sys.executable, "single-tpu-cls.py", *PRETRAIN, *TIMED],
     {}, "output/single-cls.msgpack", "fp32, 288 steps"),
    ("dataparallel", [sys.executable, "multi-tpu-dataparallel-cls.py",
                      *PRETRAIN, *TIMED],
     {}, "output/dataparallel-cls.msgpack",
     "fp32; nn.DataParallel semantics (288 steps, global batch unscaled)"),
    ("dp (DDP analog)", [sys.executable, "multi-tpu-jax-cls.py",
                         *PRETRAIN, *TIMED],
     {}, "output/dp-cls.msgpack", "fp32, mesh data axis"),
    ("amp (bf16)", [sys.executable, "multi-tpu-amp-cls.py",
                    *PRETRAIN, *TIMED],
     {}, "output/amp-cls.msgpack", "bf16 compute, fp32 masters"),
    ("shardmap (Horovod analog)", [sys.executable, "multi-tpu-shardmap-cls.py",
                                   *PRETRAIN, *TIMED],
     {}, "output/shardmap-cls.msgpack", "explicit psum, bf16 grad wire"),
    ("zero (ZeRO-3 analog)", [sys.executable, "multi-tpu-zero-cls.py",
                              *PRETRAIN, *TIMED],
     {}, "output/zero-cls.msgpack", "fully-sharded state + remat"),
    ("zero + offload", [sys.executable, "multi-tpu-zero-cls.py",
                        "--offload_opt_state", "true",
                        "--ckpt_name", "offload-cls.msgpack",
                        *PRETRAIN, "--warmup_compile", "true"],
     {}, "output/offload-cls.msgpack",
     "Adam moments in host RAM; probe n/a (jnp.copy would un-offload)"),
    ("accelerate", [sys.executable, "multi-tpu-accelerate-cls.py",
                    *PRETRAIN, *TIMED],
     {}, "output/accelerate-cls.msgpack", "prepare() convenience API"),
    ("trainer (HF Trainer analog)", [sys.executable, "multi-tpu-trainer-cls.py",
                                     "--bf16", "true", *PRETRAIN],
     {}, None,
     "save/eval every 50 steps, bf16 rotation saves, best-model reload; "
     "row is save-transport-bound: 6 x 205MB checkpoint fetches ride the "
     "tunnel, whose bulk bandwidth swings run to run — identical reruns "
     "measured 1.21 (fast period) to 7.68 min (slow); fusion changes "
     "nothing, confirming bytes not dispatches (see README)", 3),
    ("sp (ring attention, seq 512)", [sys.executable, "multi-tpu-sp-cls.py",
                                      "--max_seq_len", "512",
                                      "--train_batch_size", "8",
                                      "--dev_batch_size", "8",
                                      "--dtype", "bfloat16",
                                      *PRETRAIN, *TIMED],
     {}, "output/sp-cls.msgpack",
     "4x sequence length, batch 8, 1150 steps, bf16"),
    ("moe (bert-base-moe, upcycled)", [sys.executable, "multi-tpu-moe-cls.py",
                                       "--dtype", "bfloat16",
                                       *PRETRAIN, *TIMED],
     {}, "output/ep-cls.msgpack",
     "4 experts upcycled from the dense pretrain, bf16"),
    # ---- CPU-mesh execution-evidence rows (multi-device-only paths on the
    # one-chip image; loss/param parity pinned by tests/) ----
    ("spawn 2-proc (CPU backend)",
     [sys.executable, "multi-tpu-spawn-cls.py", "--num_processes", "2",
      "--model", "bert-small", "--data_limit", "2000", "--ckpt_name",
      "spawn-cls.msgpack"],
     {**CPU_ENV, "XLA_FLAGS": "--xla_force_host_platform_device_count=4"},
     "output/spawn-cls.msgpack",
     "2 real processes x 4 virtual devices, TCP rendezvous, bert-small; "
     "cross-process zero/pp execution pinned by tests/test_spawn.py"),
    ("tp 4x2 data*model (CPU mesh)",
     [sys.executable, "multi-tpu-tp-cls.py", "--model", "bert-tiny",
      "--max_seq_len", "64", "--data_limit", "2000",
      "--mesh_shape", '{"data": 4, "model": 2}',
      "--log_every", "1000000", "--ckpt_name", "tp-cls.msgpack"],
     CPU_ENV, "output/tp-cls.msgpack", "bert-tiny execution evidence"),
    ("pp 2-stage (CPU mesh)",
     [sys.executable, "multi-tpu-pp-cls.py", "--model", "bert-tiny",
      "--max_seq_len", "64", "--data_limit", "2000",
      "--mesh_shape", '{"stage": 2}', "--num_devices", "2",
      "--microbatches", "4",
      "--log_every", "1000000", "--ckpt_name", "pp-cls.msgpack"],
     CPU_ENV, "output/pp-cls.msgpack", "bert-tiny execution evidence"),
]

RE_MIN = re.compile(r"耗时：([\d.]+)分钟")
RE_ACC = re.compile(r"accuracy：([\d.]+)")
RE_PROBE = re.compile(r"probe steps/s：([\d.]+)")
RE_EVAL_ACC = re.compile(r"eval_accuracy ([\d.]+)")
RE_RUNTIME = re.compile(r"'train_runtime': ([\d.]+)")
TRANSIENT = ("remote_compile", "read body", "DEADLINE_EXCEEDED")


def run_row(name, argv, env_over, ckpt_path, note, timeout, repeat=1):
    """One strategy row.  ``repeat`` > 1 re-runs the command back-to-back and
    reports the MEDIAN minutes (each attempt kept in ``runs_min``) — used for
    the transport-bound trainer row, where identical reruns measured 1.21 to
    7.68 min purely with tunnel bandwidth."""
    if repeat > 1:
        rows = [run_row(name, argv, env_over, ckpt_path, note, timeout)
                for _ in range(repeat)]
        ok = [r for r in rows if "error" not in r]
        if not ok:  # all attempts failed: ship an honestly-labeled error row
            err = rows[0]
            err["note"] = (f"all {repeat} back-to-back attempts failed; "
                           + err.get("note", note))
            return err
        ok.sort(key=lambda r: r.get("minutes") or 1e9)
        # lower median for even survivor counts: a failed attempt must not
        # flip the published number to the slower (max) of two survivors
        med = ok[(len(ok) - 1) // 2]
        med["runs_min"] = [r.get("minutes") for r in ok]
        med["note"] = (f"median of {len(ok)}/{repeat} successful "
                       f"back-to-back runs; " + med["note"])
        return med
    env = dict(os.environ, **env_over)
    print(f"=== {name}: {' '.join(argv[1:])}", flush=True)
    for attempt in (1, 2):
        t0 = time.time()
        try:
            p = subprocess.run(argv, env=env, capture_output=True, text=True,
                               timeout=timeout)
        except subprocess.TimeoutExpired:
            print("    -> TIMEOUT", flush=True)
            return {"error": f"timeout after {timeout}s", "note": note,
                    "argv": argv[1:]}
        out = p.stdout + p.stderr
        if p.returncode == 0:
            break
        if attempt == 1 and any(t in out for t in TRANSIENT):
            print(f"    -> transient failure (rc {p.returncode}), retrying",
                  flush=True)
            continue
        print(out[-3000:])
        return {"error": p.returncode, "note": note, "argv": argv[1:]}
    minutes = RE_MIN.findall(out)
    accs = RE_ACC.findall(out)
    probes = RE_PROBE.findall(out)
    eval_accs = RE_EVAL_ACC.findall(out)
    runtime = RE_RUNTIME.findall(out)
    row = {
        "minutes": float(minutes[-1]) if minutes else (
            round(float(runtime[-1]) / 60, 4) if runtime else None),
        "probe_steps_per_sec": float(probes[-1]) if probes else None,
        "accuracy": float(accs[-1]) if accs else (
            float(eval_accs[-1]) if eval_accs else None),
        "checkpoint": ckpt_path if ckpt_path and os.path.exists(ckpt_path)
        else ("missing!" if ckpt_path else "output/auto/checkpoint-*"),
        "wall_s_incl_startup": round(time.time() - t0, 1),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "note": note,
        "argv": argv[1:],
    }
    print(f"    -> {row['minutes']} min, probe "
          f"{row['probe_steps_per_sec']} steps/s, acc {row['accuracy']}",
          flush=True)
    return row


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default=None,
                    help="comma-separated substrings of row names to run "
                         "(others keep their existing matrix.json entry)")
    ap.add_argument("--out", default="output/matrix.json")
    ap.add_argument("--timeout", type=int, default=1800)
    args = ap.parse_args()
    os.chdir(ROOT)
    if not os.path.exists(CKPT):
        sys.exit(f"{CKPT} missing — run pretrain-tpu.py first")

    results = {}
    if args.only and os.path.exists(args.out):
        with open(args.out) as f:
            prior = json.load(f)
        # accept both the current {"meta":…, "rows":…} artifact and the
        # legacy flat {row: …} format, so --only never discards old rows
        results = prior.get("rows") if "rows" in prior else {
            k: v for k, v in prior.items() if k != "meta"}
    wanted = [w.strip() for w in args.only.split(",")] if args.only else None
    fresh = set()
    for name, argv, env_over, ckpt_path, note, *rest in RUNS:
        if wanted and not any(w in name for w in wanted):
            continue
        results[name] = run_row(name, argv, env_over, ckpt_path, note,
                                args.timeout, repeat=rest[0] if rest else 1)
        fresh.add(name)
    # carried-over rows were measured under a (possibly different) earlier
    # session/protocol — stamp them so the single meta.protocol block can't
    # silently claim one methodology for rows it didn't produce
    for name, row in results.items():
        if isinstance(row, dict):
            row.pop("carried_over", None)
            if name not in fresh:
                row["carried_over"] = True

    import jax

    artifact = {
        "meta": {
            "device": str(jax.devices()[0].device_kind),
            "platform": jax.devices()[0].platform,
            "protocol": ("1 epoch, constant LR 3e-5, batch 32 (sp: 8), "
                         "seq 128 (sp: 512), init_from "
                         "output/pretrained.msgpack + --init_head, dev off; "
                         "epoch timed after AOT compile (warmup_compile), "
                         "probe = 30 re-fed steps before the epoch"),
            "written_by": "scripts/run_matrix.py",
        },
        "rows": results,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2, ensure_ascii=False)
    print(f"\nwrote {args.out}")

    def table(rows):
        print("\n| Strategy | min/epoch (post-compile) | probe steps/s | dev accuracy |")
        print("|---|---|---|---|")
        for name, row in rows:
            if "error" in row:
                print(f"| {name} | FAILED: {row['error']} | — | — |")
            else:
                probe = (f"{row['probe_steps_per_sec']:.1f}"
                         if row.get("probe_steps_per_sec") else "—")
                mins = (f"{row['minutes']:.3f}"
                        if row.get("minutes") is not None else "—")
                acc = (f"{row['accuracy']:.4f}"
                       if row.get("accuracy") is not None else "—")
                stale = " (carried over)" if row.get("carried_over") else ""
                print(f"| {name}{stale} | {mins} | {probe} | {acc} |")

    # the CPU-mesh rows are execution evidence for multi-device-only paths
    # (smaller models, data_limit) — never mix them into the TPU comparison
    main_rows = [(n, r) for n, r in results.items() if "CPU" not in n]
    ev_rows = [(n, r) for n, r in results.items() if "CPU" in n]
    table(main_rows)
    if ev_rows:
        print("\nExecution evidence (CPU virtual mesh, reduced model/data — "
              "not comparable to the TPU rows above):")
        table(ev_rows)


if __name__ == "__main__":
    main()
