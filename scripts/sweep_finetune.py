#!/usr/bin/env python
"""Fine-tune recipe sweep: lr x pretrain-checkpoint -> dev accuracy."""
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import itertools
import re

CKPTS = [c for c in ("output/pretrained.msgpack", "output/pretrained_r150.msgpack")
         if os.path.exists(c)]
LRS = ["2e-5", "3e-5", "5e-5"]

for ckpt, lr in itertools.product(CKPTS, LRS):
    p = subprocess.run(
        [sys.executable, "multi-tpu-jax-cls.py", "--dtype", "bfloat16",
         "--init_from", ckpt, "--learning_rate", lr,
         "--log_every", "1000000000", "--dev", "false",
         "--ckpt_name", "sweep-tmp.msgpack"],
        capture_output=True, text=True, timeout=600)
    accs = re.findall(r"accuracy：([\d.]+)", p.stdout)
    mins = re.findall(r"耗时：([\d.]+)", p.stdout)
    print(f"{os.path.basename(ckpt):28s} lr={lr:6s} "
          f"acc={accs[-1] if accs else 'FAIL'} min={mins[-1] if mins else '?'}",
          flush=True)
    if not accs:
        print(p.stdout[-1500:], p.stderr[-1500:])
