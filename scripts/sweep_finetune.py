#!/usr/bin/env python
"""Fine-tune recipe sweep: lr x pretrain-checkpoint -> dev accuracy.

Positional args select grid rows by name under the exact-name rule
(``pdnlp_tpu.utils.sweeps``): ``pretrained_lr2e-5`` runs exactly one cell;
``lr2e-5`` substring-selects that lr across every checkpoint.
"""
import itertools
import os
import re
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pdnlp_tpu.utils.sweeps import make_selected, parse_only  # noqa: E402

CKPTS = [c for c in ("output/pretrained.msgpack", "output/pretrained_r150.msgpack")
         if os.path.exists(c)]
LRS = ["2e-5", "3e-5", "5e-5"]


def main():
    grid = {}
    for ckpt, lr in itertools.product(CKPTS, LRS):
        stem = os.path.splitext(os.path.basename(ckpt))[0]
        grid[f"{stem}_lr{lr}"] = (ckpt, lr)

    selected = make_selected(parse_only(sys.argv[1:]), grid)
    for name, (ckpt, lr) in grid.items():
        if not selected(name):
            continue
        p = subprocess.run(
            [sys.executable, "multi-tpu-jax-cls.py", "--dtype", "bfloat16",
             "--init_from", ckpt, "--learning_rate", lr,
             "--log_every", "1000000000", "--dev", "false",
             "--ckpt_name", "sweep-tmp.msgpack"],
            capture_output=True, text=True, timeout=600)
        accs = re.findall(r"accuracy：([\d.]+)", p.stdout)
        mins = re.findall(r"耗时：([\d.]+)", p.stdout)
        print(f"{os.path.basename(ckpt):28s} lr={lr:6s} "
              f"acc={accs[-1] if accs else 'FAIL'} "
              f"min={mins[-1] if mins else '?'}", flush=True)
        if not accs:
            print(p.stdout[-1500:], p.stderr[-1500:])


if __name__ == "__main__":
    main()
