#!/usr/bin/env python
"""Measure dropout RNG cost: threefry vs rbg keys for the train step."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "output/xla_cache")

from pdnlp_tpu.models import bert, get_config
from pdnlp_tpu.train.optim import build_optimizer
from pdnlp_tpu.train.steps import build_train_step, init_state
from pdnlp_tpu.utils.config import Args

N = 50
B, S = 32, 128

args = Args(strategy="dp", dtype="bfloat16")
cfg = get_config(args.model, vocab_size=16000, num_labels=6,
                 dropout=args.dropout, attn_dropout=args.attn_dropout)
key = jax.random.PRNGKey(0)
params = bert.init_params(key, cfg)
tx = build_optimizer(params, args)
batch = jax.device_put({
    "input_ids": jnp.ones((B, S), jnp.int32),
    "token_type_ids": jnp.zeros((B, S), jnp.int32),
    "attention_mask": jnp.ones((B, S), jnp.int32),
    "label": jnp.zeros((B,), jnp.int32),
    "example_weight": jnp.ones((B,), jnp.float32),
})


def timeit(name, fn):
    out = fn()
    jax.block_until_ready(out)
    float(jnp.sum(out).astype(jnp.float32))
    t0 = time.time()
    for _ in range(N):
        out = fn()
    float(jnp.sum(out).astype(jnp.float32))
    print(f"{name:30s}: {(time.time()-t0)/N*1e3:7.2f} ms")


step = jax.jit(build_train_step(cfg, tx, args))
for impl in ("threefry2x32", "rbg", "unsafe_rbg"):
    state = init_state(key, cfg, tx, rng=jax.random.key(0, impl=impl),
                       params=params)
    try:
        timeit(f"full step rng={impl}", lambda: step(state, batch)[1]["loss"])
    except Exception as e:
        print(f"{impl}: FAILED {type(e).__name__}: {e}")
