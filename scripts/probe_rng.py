#!/usr/bin/env python
"""Measure dropout RNG cost: threefry vs rbg keys for the train step."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "output/xla_cache")

from pdnlp_tpu.models import bert, get_config
from pdnlp_tpu.train.optim import build_optimizer
from pdnlp_tpu.train.steps import build_train_step, init_state
from pdnlp_tpu.utils.config import Args

N = 50
B, S = 32, 128

args = Args(strategy="dp", dtype="bfloat16")
cfg = get_config(args.model, vocab_size=16000, num_labels=6,
                 dropout=args.dropout, attn_dropout=args.attn_dropout)
key = jax.random.PRNGKey(0)
params = bert.init_params(key, cfg)
tx = build_optimizer(params, args)
batch = jax.device_put({
    "input_ids": jnp.ones((B, S), jnp.int32),
    "token_type_ids": jnp.zeros((B, S), jnp.int32),
    "attention_mask": jnp.ones((B, S), jnp.int32),
    "label": jnp.zeros((B,), jnp.int32),
    "example_weight": jnp.ones((B,), jnp.float32),
})


def timeit_step(name, step_fn, s):
    """Donated step (jaxlint R5): state threads through the loop — the
    input buffers are consumed each call, exactly like the real loop."""
    s, m = step_fn(s, batch)  # warmup/compile
    jax.block_until_ready(m["loss"])
    float(jnp.sum(m["loss"]).astype(jnp.float32))
    t0 = time.time()
    for _ in range(N):
        s, m = step_fn(s, batch)
    float(jnp.sum(m["loss"]).astype(jnp.float32))
    print(f"{name:30s}: {(time.time()-t0)/N*1e3:7.2f} ms")


step = jax.jit(build_train_step(cfg, tx, args), donate_argnums=0)
for impl in ("threefry2x32", "rbg", "unsafe_rbg"):
    # fresh params per impl: the donated step consumed the previous
    # incarnation's buffers
    state = init_state(key, cfg, tx, rng=jax.random.key(0, impl=impl),
                       params=bert.init_params(key, cfg))
    try:
        timeit_step(f"full step rng={impl}", step, state)
    except Exception as e:
        print(f"{impl}: FAILED {type(e).__name__}: {e}")
