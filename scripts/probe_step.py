#!/usr/bin/env python
"""Bisect the device step cost: which part of the 39ms/step is what."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import optax

jax.config.update("jax_compilation_cache_dir", "output/xla_cache")

from pdnlp_tpu.models import bert, get_config
from pdnlp_tpu.train.optim import build_optimizer
from pdnlp_tpu.train.steps import build_train_step, init_state, weighted_ce
from pdnlp_tpu.utils.config import Args

N = 50
B, S = 32, 128

args = Args(strategy="dp", dtype="bfloat16")
cfg = get_config(args.model, vocab_size=16000, num_labels=6,
                 dropout=args.dropout, attn_dropout=args.attn_dropout)
key = jax.random.PRNGKey(0)
params = bert.init_params(key, cfg)
tx = build_optimizer(params, args)
state = init_state(key, cfg, tx, rng=jax.random.key(0), params=params)
batch = {
    "input_ids": jnp.ones((B, S), jnp.int32),
    "token_type_ids": jnp.zeros((B, S), jnp.int32),
    "attention_mask": jnp.ones((B, S), jnp.int32),
    "label": jnp.zeros((B,), jnp.int32),
    "example_weight": jnp.ones((B,), jnp.float32),
}
batch = jax.device_put(batch)


def timeit(name, fn, *a, donated=False):
    # warmup/compile
    out = fn(*a)
    jax.block_until_ready(out)
    sync = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(sync).astype(jnp.float32))
    t0 = time.time()
    for _ in range(N):
        out = fn(*a)
    sync = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(sync).astype(jnp.float32))
    dt = (time.time() - t0) / N * 1e3
    print(f"{name:34s}: {dt:7.2f} ms")
    return dt


def timeit_step(name, step_fn, cfg_for_state):
    """Time a DONATED full train step the way the real loop runs it:
    state threads through each iteration (jaxlint R5 — donation keeps the
    step at 1x state HBM instead of a transient 2x).  The step consumes
    its input buffers, so it gets a PRIVATE state on fresh params — the
    shared probe `state`/`params` above stay live for the forward-only
    and optimizer-only sections."""
    s = init_state(key, cfg_for_state, tx, rng=jax.random.key(0),
                   params=bert.init_params(key, cfg_for_state))
    s, m = step_fn(s, batch)  # warmup/compile
    jax.block_until_ready(m["loss"])
    float(jnp.sum(m["loss"]).astype(jnp.float32))
    t0 = time.time()
    for _ in range(N):
        s, m = step_fn(s, batch)
    float(jnp.sum(m["loss"]).astype(jnp.float32))
    dt = (time.time() - t0) / N * 1e3
    print(f"{name:34s}: {dt:7.2f} ms")
    return dt


# 1. full train step (the benched program), donated + state-threaded
full = jax.jit(build_train_step(cfg, tx, args), donate_argnums=0)
timeit_step("full step (dropout on)", full, cfg)

# 2. no-dropout variant
cfg_nd = get_config(args.model, vocab_size=16000, num_labels=6,
                    dropout=0.0, attn_dropout=0.0)
full_nd = jax.jit(build_train_step(cfg_nd, tx, args), donate_argnums=0)
timeit_step("full step (dropout off)", full_nd, cfg_nd)

dtype = jnp.bfloat16

# 3. forward only (train mode, dropout on)
def fwd(params, batch, rng):
    logits = bert.classify(params, cfg, batch, dtype=dtype, deterministic=False,
                           rng=rng)
    return weighted_ce(logits, batch["label"], batch["example_weight"])[0]

fwd_j = jax.jit(fwd)
rng = jax.random.key(1)
timeit("forward only (dropout on)", lambda: fwd_j(state["params"], batch, rng))

def fwd_det(params, batch):
    logits = bert.classify(params, cfg, batch, dtype=dtype, deterministic=True)
    return weighted_ce(logits, batch["label"], batch["example_weight"])[0]

fwd_det_j = jax.jit(fwd_det)
timeit("forward only (deterministic)", lambda: fwd_det_j(state["params"], batch))

# 4. fwd+bwd, no optimizer
grad_j = jax.jit(jax.grad(fwd))
timeit("fwd+bwd (dropout on)", lambda: grad_j(state["params"], batch, rng))

grad_det_j = jax.jit(jax.grad(fwd_det))
timeit("fwd+bwd (deterministic)", lambda: grad_det_j(state["params"], batch))

# 5. optimizer only
grads = grad_j(state["params"], batch, rng)
grads = jax.block_until_ready(grads)

def opt_only(g, opt_state, params):
    updates, opt_state = tx.update(g, opt_state, params)
    return optax.apply_updates(params, updates)

opt_j = jax.jit(opt_only)
timeit("AdamW update only", lambda: opt_j(grads, state["opt_state"], state["params"]))

# 6. pallas attention variant
args_p = args.replace(attention_impl="pallas")
full_p = jax.jit(build_train_step(cfg, tx, args_p), donate_argnums=0)
timeit_step("full step (pallas attn, dropout on)", full_p, cfg)

args_pn = args_p
full_pn = jax.jit(build_train_step(cfg_nd, tx, args_pn), donate_argnums=0)
timeit_step("full step (pallas, dropout off)", full_pn, cfg_nd)
