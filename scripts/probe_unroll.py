#!/usr/bin/env python
"""Does unrolling the 12-layer lax.scan buy step time on the chip?"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "output/xla_cache")

from pdnlp_tpu.models import bert, get_config
from pdnlp_tpu.train.optim import build_optimizer
from pdnlp_tpu.train.steps import build_train_step, init_state
from pdnlp_tpu.utils.config import Args

N = 50
B, S = 32, 128

args = Args(strategy="dp", dtype="bfloat16")
cfg = get_config(args.model, vocab_size=6013, num_labels=6)
key = jax.random.PRNGKey(0)
params = bert.init_params(key, cfg)
tx = build_optimizer(params, args)
state = init_state(key, cfg, tx, rng=jax.random.key(0, impl="rbg"),
                   params=params)
batch = jax.device_put({
    "input_ids": jnp.ones((B, S), jnp.int32),
    "token_type_ids": jnp.zeros((B, S), jnp.int32),
    "attention_mask": jnp.ones((B, S), jnp.int32),
    "label": jnp.zeros((B,), jnp.int32),
    "example_weight": jnp.ones((B,), jnp.float32),
})

def timeit(name, fn):
    out = fn()
    jax.block_until_ready(out)
    float(jnp.sum(out).astype(jnp.float32))
    t0 = time.time()
    for _ in range(N):
        out = fn()
    float(jnp.sum(out).astype(jnp.float32))
    print(f"{name:24s}: {(time.time()-t0)/N*1e3:7.2f} ms")


# scan_unroll=1 is the rolled scan, 12 == full unroll (also the None default)
for unroll in (1, 2, 4, 12):
    step = jax.jit(build_train_step(cfg, tx, args.replace(scan_unroll=unroll)))
    timeit(f"unroll={unroll}", lambda: step(state, batch)[1]["loss"])
