#!/usr/bin/env python
"""Does unrolling the 12-layer lax.scan buy step time on the chip?"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "output/xla_cache")

import pdnlp_tpu.models.bert as bert_mod
from pdnlp_tpu.models import bert, get_config
from pdnlp_tpu.train.optim import build_optimizer
from pdnlp_tpu.train.steps import build_train_step, init_state
from pdnlp_tpu.utils.config import Args

N = 50
B, S = 32, 128

args = Args(strategy="dp", dtype="bfloat16")
cfg = get_config(args.model, vocab_size=6013, num_labels=6)
key = jax.random.PRNGKey(0)
params = bert.init_params(key, cfg)
tx = build_optimizer(params, args)
state = init_state(key, cfg, tx, rng=jax.random.key(0, impl="rbg"),
                   params=params)
batch = jax.device_put({
    "input_ids": jnp.ones((B, S), jnp.int32),
    "token_type_ids": jnp.zeros((B, S), jnp.int32),
    "attention_mask": jnp.ones((B, S), jnp.int32),
    "label": jnp.zeros((B,), jnp.int32),
    "example_weight": jnp.ones((B,), jnp.float32),
})

orig_scan = jax.lax.scan


def timeit(name, fn):
    out = fn()
    jax.block_until_ready(out)
    float(jnp.sum(out).astype(jnp.float32))
    t0 = time.time()
    for _ in range(N):
        out = fn()
    float(jnp.sum(out).astype(jnp.float32))
    print(f"{name:24s}: {(time.time()-t0)/N*1e3:7.2f} ms")


for unroll in (1, 2, 4, 12):
    def scan_u(f, init, xs, **kw):
        kw.pop("unroll", None)
        return orig_scan(f, init, xs, unroll=unroll, **kw)

    bert_mod.jax.lax.scan = scan_u if unroll > 1 else orig_scan
    try:
        step = jax.jit(build_train_step(cfg, tx, args))
        timeit(f"unroll={unroll}", lambda: step(state, batch)[1]["loss"])
    finally:
        bert_mod.jax.lax.scan = orig_scan
