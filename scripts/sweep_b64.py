#!/usr/bin/env python
"""Accuracy sweep for the batch-64 headline recipe (r5).

Batch 64 amortizes the step's fixed optimizer cost (+36% examples/s,
~49% MFU — results/profile_r05.json); this sweeps lr x ema_decay x epochs
at that batch from the two-phase pretrain warm start and records the full
in-loop eval history so time-to-accuracy can be read per config.

Writes/merges ``results/recipe_b64_sweep.json``.  Run on the chip.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PATH = os.path.join(REPO, "results", "recipe_b64_sweep.json")

sys.path.insert(0, REPO)

from pdnlp_tpu.utils.sweeps import make_selected, parse_only  # noqa: E402

CODE = r"""
import json, sys, time
spec = json.loads(sys.argv[1])
import jax
jax.config.update('jax_compilation_cache_dir', 'output/xla_cache')
from pdnlp_tpu.train.run import build_parallel_trainer
from pdnlp_tpu.utils.config import Args
args = Args(**spec)
tr, tl, dl = build_parallel_trainer(args, mode='dp')
tr.warmup_compile(tl, dl)
minutes = tr.train(tl, dl)
loss, acc = tr.dev(dl)
print(json.dumps({
    "total_minutes": round(minutes, 4),
    "final_accuracy": round(acc, 4),
    "best_accuracy": round(tr.best_accuracy, 4),
    "eval_history": [{"minutes": round(e["minutes"], 4),
                      "accuracy": round(e["accuracy"], 4)}
                     for e in tr.eval_history],
}))
"""


def run(name, **kw):
    spec = dict(strategy="dp", dtype="bfloat16", train_batch_size=64,
                fuse_steps=4, dev=True, eval_step=48, log_every=10 ** 9,
                lr_schedule="warmup_linear", ema_decay=0.99, epochs=3,
                init_from="output/pretrained.msgpack", init_head=True)
    spec.update(kw)
    out = subprocess.run([sys.executable, "-c", CODE, json.dumps(spec)],
                         capture_output=True, text=True, cwd=REPO)
    if out.returncode != 0:
        print(f"{name}: FAILED\n{out.stderr[-2000:]}", file=sys.stderr)
        return None
    r = json.loads(out.stdout.strip().splitlines()[-1])
    r["config"] = {k: spec[k] for k in
                   ("train_batch_size", "learning_rate", "ema_decay",
                    "epochs", "fuse_steps", "eval_step", "gelu",
                    "init_from") if k in spec}
    r["config"].setdefault("learning_rate", 3e-5)
    print(f"{name}: best={r['best_accuracy']} total={r['total_minutes']}min",
          file=sys.stderr)
    return r


def main():
    res = json.load(open(PATH)) if os.path.exists(PATH) else {"runs": {}}
    grid = {}
    for lr in (3e-5, 4.5e-5, 6e-5):
        for ema in (0.99, 0.995):
            grid[f"b64_lr{lr:g}_ema{ema:g}_3ep"] = dict(
                learning_rate=lr, ema_decay=ema, epochs=3)
    # refinement round: lr 6e-5 won the first grid at 0.5813/0.36min —
    # probe above it and around the epoch count
    for lr in (8e-5, 1e-4):
        grid[f"b64_lr{lr:g}_ema0.99_3ep"] = dict(
            learning_rate=lr, ema_decay=0.99, epochs=3)
    grid["b64_lr6e-05_ema0.99_2ep"] = dict(
        learning_rate=6e-5, ema_decay=0.99, epochs=2)
    grid["b64_lr8e-05_ema0.99_2ep"] = dict(
        learning_rate=8e-5, ema_decay=0.99, epochs=2)
    grid["b64_lr6e-05_ema0.99_4ep"] = dict(
        learning_rate=6e-5, ema_decay=0.99, epochs=4)
    # tanh round: the fully tanh-pretrained trunk (pretrained-tanh.msgpack)
    # shifted the optimum — a single COMPRESSED-schedule epoch measured
    # 0.5975 (vs 0.5887 at 3ep), so sweep the epoch count down and lr
    # around it.  gelu must match the trunk's activation (bench.py cache
    # keying note).
    tanh = dict(gelu="tanh", init_from="output/pretrained-tanh.msgpack")
    for lr in (4.5e-5, 6e-5, 8e-5, 1e-4):
        grid[f"tanh_b64_lr{lr:g}_ema0.99_1ep"] = dict(
            learning_rate=lr, ema_decay=0.99, epochs=1, **tanh)
    for lr in (6e-5, 8e-5):
        grid[f"tanh_b64_lr{lr:g}_ema0.99_2ep"] = dict(
            learning_rate=lr, ema_decay=0.99, epochs=2, **tanh)
    grid["tanh_b64_lr6e-05_ema0.995_1ep"] = dict(
        learning_rate=6e-5, ema_decay=0.995, epochs=1, **tanh)
    grid["tanh_b64_lr6e-05_ema0.99_3ep"] = dict(
        learning_rate=6e-5, ema_decay=0.99, epochs=3, **tanh)
    # pin the 1-epoch optimum: lr half-steps around the 6e-5 winner, and a
    # finer eval cadence (fuse_steps 4 divides 24, keeping eval boundaries
    # exact; more best-candidates per epoch at ~2s extra eval cost with
    # the device-cached dev set)
    for lr in (5e-5, 7e-5):
        grid[f"tanh_b64_lr{lr:g}_ema0.99_1ep"] = dict(
            learning_rate=lr, ema_decay=0.99, epochs=1, **tanh)
    grid["tanh_b64_lr6e-05_ema0.99_1ep_eval24"] = dict(
        learning_rate=6e-5, ema_decay=0.99, epochs=1, eval_step=24, **tanh)
    # exact-name row selection (pdnlp_tpu.utils.sweeps): this grid has real
    # substring-superset collisions ('b64_lr6e-05_ema0.99_3ep' is a
    # substring of its 'tanh_...' sibling) that would silently re-run extra
    # chip-time rows
    selected = make_selected(parse_only(sys.argv[1:]), grid)

    for name, kw in grid.items():
        if not selected(name):
            continue
        if name in res["runs"] and res["runs"][name]:
            continue
        res["runs"][name] = run(name, **kw)
        tmp = PATH + ".tmp"  # atomic: an interrupt must not eat prior runs
        json.dump(res, open(tmp, "w"), indent=2)
        os.replace(tmp, PATH)
    best = max((r for r in res["runs"].values() if r),
               key=lambda r: r["best_accuracy"], default=None)
    print(json.dumps({"best": best}, indent=2))


if __name__ == "__main__":
    main()
