#!/usr/bin/env python
"""Round 2: schedule x epochs combinations on the best pretrain ckpt.

Positional args select rows by name under the exact-name rule
(``pdnlp_tpu.utils.sweeps``): ``2ep-wl-5e-5`` runs exactly that cell;
``wl`` substring-selects every warmup-linear row.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "output/xla_cache")

from pdnlp_tpu.train.run import build_parallel_trainer
from pdnlp_tpu.utils.config import Args
from pdnlp_tpu.utils.sweeps import make_selected, parse_only

CKPT = "output/pretrained_p30.msgpack"


def run(tag, schedule_fn=None, **kw):
    import pdnlp_tpu.parallel.execution as ex
    import pdnlp_tpu.train.optim as optim_mod

    orig = optim_mod.build_optimizer
    if schedule_fn is not None:
        def patched(params, args, schedule=None):
            return orig(params, args, schedule=schedule_fn)
        optim_mod.build_optimizer = patched
        ex_orig = ex.build_optimizer
        ex.build_optimizer = patched
    try:
        args = Args(strategy="exp", dtype="bfloat16", init_from=CKPT,
                    dev=True, eval_step=50, log_every=10 ** 9,
                    ckpt_name="sweep-tmp.msgpack", **kw)
        tr, loader, dev_loader = build_parallel_trainer(args, mode="dp")
        tr.train(loader, dev_loader)
        print(f"{tag:30s} best={tr.best_accuracy:.4f}", flush=True)
    finally:
        if schedule_fn is not None:
            optim_mod.build_optimizer = orig
            ex.build_optimizer = ex_orig


def wl(peak, total):
    """The shipped warmup_linear schedule, built by the same helper the
    framework uses (one formula, one place: optim.make_schedule)."""
    from pdnlp_tpu.train.optim import make_schedule

    return make_schedule(Args(lr_schedule="warmup_linear",
                              learning_rate=peak), total)


def main():
    grid = {
        "2ep-wl-5e-5": dict(schedule_fn=wl(5e-5, 576), epochs=2),
        "2ep-wl-3e-5": dict(schedule_fn=wl(3e-5, 576), epochs=2),
        "3ep-wl-5e-5": dict(schedule_fn=wl(5e-5, 864), epochs=3),
        "3ep-const-3e-5": dict(epochs=3),
        "2ep-const-5e-5": dict(learning_rate=5e-5, epochs=2),
    }
    selected = make_selected(parse_only(sys.argv[1:]), grid)
    for name, kw in grid.items():
        if selected(name):
            run(name, **kw)


if __name__ == "__main__":
    main()
