#!/usr/bin/env python
"""Supervised-stage recipe sweep: how many sft epochs, and does restoring the
trained head at fine-tune time help?

Assumes the MLM phase-1 checkpoint already exists (pretrain-tpu.py writes
output/pretrained-mlm.msgpack when sft follows; a bare MLM artifact at
output/pretrained.msgpack works too — pass it via ``--mlm PATH``).

Positional args select grid rows by name under the exact-name rule
(``pdnlp_tpu.utils.sweeps``): ``sft3-ref1ep-head`` runs one cell,
``2ep-wl`` substring-selects the 2-epoch recipe across all sft depths.

Prints best-of-epoch dev accuracy per (sft_epochs, fine-tune recipe) cell.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pdnlp_tpu.train.pretrain import run_supervised_stage
from pdnlp_tpu.train.run import build_parallel_trainer
from pdnlp_tpu.utils.config import Args, enable_compilation_cache, \
    pop_cli_flag
from pdnlp_tpu.utils.sweeps import make_selected, parse_only

enable_compilation_cache(Args())


def finetune(tag, ckpt, **kw):
    args = Args(strategy="exp", dtype="bfloat16", init_from=ckpt,
                dev=True, eval_step=50, log_every=10 ** 9,
                ckpt_name="sweep-tmp.msgpack", **kw)
    tr, loader, dev_loader = build_parallel_trainer(args, mode="dp")
    tr.train(loader, dev_loader)
    print(f"{tag:44s} best={tr.best_accuracy:.4f}", flush=True)
    return tr.best_accuracy


def main():
    argv, mlm = pop_cli_flag(sys.argv[1:], "--mlm",
                             default="output/pretrained-mlm.msgpack")
    if argv and argv[0].endswith(".msgpack"):
        # pre-flag invocation shape: a bare checkpoint path as argv[1]
        mlm = argv.pop(0)

    grid = {}
    for sft_epochs in (1, 2, 3, 5):
        # reference's exact protocol: 1 epoch, constant 3e-5
        grid[f"sft{sft_epochs}-ref1ep-fresh"] = (sft_epochs, dict())
        grid[f"sft{sft_epochs}-ref1ep-head"] = (sft_epochs,
                                                dict(init_head=True))
        # shipped recipe: 2 epochs, linear warmup->decay
        grid[f"sft{sft_epochs}-2ep-wl-head"] = (
            sft_epochs, dict(init_head=True, epochs=2,
                             lr_schedule="warmup_linear"))

    selected = make_selected(parse_only(argv), grid)
    for name, (sft_epochs, kw) in grid.items():
        if not selected(name):
            continue
        sft_ckpt = f"output/sft-e{sft_epochs}.msgpack"
        if not os.path.exists(sft_ckpt):
            run_supervised_stage(Args(
                strategy="sft", dtype="bfloat16", init_from=mlm,
                epochs=sft_epochs, learning_rate=3e-5,
                lr_schedule="warmup_linear", dev=False,
                log_every=10 ** 9, ckpt_name=os.path.basename(sft_ckpt)))
        finetune(name, sft_ckpt, **kw)


if __name__ == "__main__":
    main()
