#!/usr/bin/env python
"""Supervised-stage recipe sweep: how many sft epochs, and does restoring the
trained head at fine-tune time help?

Assumes the MLM phase-1 checkpoint already exists (pretrain-tpu.py writes
output/pretrained-mlm.msgpack when sft follows; a bare MLM artifact at
output/pretrained.msgpack works too — pass it as argv[1]).

Prints best-of-epoch dev accuracy per (sft_epochs, fine-tune recipe) cell.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pdnlp_tpu.train.pretrain import run_supervised_stage
from pdnlp_tpu.train.run import build_parallel_trainer
from pdnlp_tpu.utils.config import Args, enable_compilation_cache

enable_compilation_cache(Args())

MLM = sys.argv[1] if len(sys.argv) > 1 else "output/pretrained-mlm.msgpack"


def finetune(tag, ckpt, **kw):
    args = Args(strategy="exp", dtype="bfloat16", init_from=ckpt,
                dev=True, eval_step=50, log_every=10 ** 9,
                ckpt_name="sweep-tmp.msgpack", **kw)
    tr, loader, dev_loader = build_parallel_trainer(args, mode="dp")
    tr.train(loader, dev_loader)
    print(f"{tag:44s} best={tr.best_accuracy:.4f}", flush=True)
    return tr.best_accuracy


for sft_epochs in (1, 2, 3, 5):
    sft_ckpt = f"output/sft-e{sft_epochs}.msgpack"
    if not os.path.exists(sft_ckpt):
        run_supervised_stage(Args(
            strategy="sft", dtype="bfloat16", init_from=MLM,
            epochs=sft_epochs, learning_rate=3e-5,
            lr_schedule="warmup_linear", dev=False,
            log_every=10 ** 9, ckpt_name=os.path.basename(sft_ckpt)))
    # reference's exact protocol: 1 epoch, constant 3e-5
    finetune(f"sft{sft_epochs} -> ref 1ep const, fresh head", sft_ckpt)
    finetune(f"sft{sft_epochs} -> ref 1ep const, +head", sft_ckpt,
             init_head=True)
    # shipped recipe: 2 epochs, linear warmup->decay
    finetune(f"sft{sft_epochs} -> 2ep warmup_linear, +head", sft_ckpt,
             init_head=True, epochs=2, lr_schedule="warmup_linear")
