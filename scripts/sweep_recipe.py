#!/usr/bin/env python
"""Fine-tune recipe experiments: warmup schedule, layerwise LR decay, 2-epoch.

Runs in-process (TPU) with the best pretrain checkpoint; prints best-of-epoch
dev accuracy per recipe.

Positional args select rows by name under the exact-name rule
(``pdnlp_tpu.utils.sweeps``): ``cosine-3e-5`` runs exactly that recipe;
``cosine`` substring-selects the family.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import optax

jax.config.update("jax_compilation_cache_dir", "output/xla_cache")

from pdnlp_tpu.train.run import build_parallel_trainer
from pdnlp_tpu.train.optim import build_optimizer
from pdnlp_tpu.utils.config import Args
from pdnlp_tpu.utils.sweeps import make_selected, parse_only

CKPT = "output/pretrained_p30.msgpack"


def run(tag, **kw):
    import pdnlp_tpu.train.optim as optim_mod

    schedule_fn = kw.pop("schedule_fn", None)
    orig = optim_mod.build_optimizer
    if schedule_fn is not None:
        def patched(params, args, schedule=None):
            return orig(params, args, schedule=schedule_fn)
        optim_mod.build_optimizer = patched
        # execution.py imported the symbol directly
        import pdnlp_tpu.parallel.execution as ex
        ex_orig = ex.build_optimizer
        ex.build_optimizer = patched
    try:
        args = Args(strategy="exp", dtype="bfloat16", init_from=CKPT,
                    dev=True, eval_step=50, log_every=10 ** 9,
                    ckpt_name="sweep-tmp.msgpack", **kw)
        tr, loader, dev_loader = build_parallel_trainer(args, mode="dp")
        tr.train(loader, dev_loader)
        print(f"{tag:26s} best={tr.best_accuracy:.4f}", flush=True)
    finally:
        if schedule_fn is not None:
            optim_mod.build_optimizer = orig
            ex.build_optimizer = ex_orig


TOTAL = 288


def main():
    grid = {
        "baseline-const-3e-5": dict(),
        "cosine-3e-5": dict(schedule_fn=optax.warmup_cosine_decay_schedule(
            0.0, 3e-5, warmup_steps=17, decay_steps=TOTAL)),
        "cosine-5e-5": dict(schedule_fn=optax.warmup_cosine_decay_schedule(
            0.0, 5e-5, warmup_steps=17, decay_steps=TOTAL)),
        "linear-5e-5": dict(schedule_fn=optax.join_schedules(
            [optax.linear_schedule(0.0, 5e-5, 17),
             optax.linear_schedule(5e-5, 0.0, TOTAL - 17)], [17])),
        "2ep-const-3e-5": dict(epochs=2),
    }
    selected = make_selected(parse_only(sys.argv[1:]), grid)
    for name, kw in grid.items():
        if selected(name):
            run(name, **kw)


if __name__ == "__main__":
    main()
