#!/usr/bin/env python
"""Offline int8 weight quantization of a committed checkpoint.

Produces the serving artifact ``--serve_dtype int8`` can load directly:
every dense block's kernel stored as per-channel symmetric int8 + one fp32
scale per output channel (``pdnlp_tpu.serve.quant`` — the identical math
the engine applies when quantizing a float checkpoint on the fly, so the
two routes can never disagree).  Calibration is weight-only: no data, no
device — this runs anywhere the checkpoint file does.

    python scripts/quantize_ckpt.py output/dp-cls.msgpack
    # -> output/dp-cls.int8.msgpack + a per-block error report

    python serve_tpu.py --serve_dtype int8 --ckpt output/dp-cls.int8.msgpack

``--kv_calib MODEL`` additionally emits the int8 KV-cache scale tables the
generative decode engine consumes (``--kv_dtype int8``): per-(layer, head,
channel) symmetric scales from the SEEDED synthetic causal forward in
``pdnlp_tpu.models.decoder.calibrate_kv_scales`` — the exact computation
the engine runs when self-calibrating at warmup, so the offline artifact
and the online fallback can never disagree.  The tables land beside the
INPUT checkpoint as ``<stem>.kvscales.msgpack`` through the same
crash-atomic manifest-verified publish, and ``DecodeEngine`` auto-loads
them when the checkpoint swaps in.  The decoder's LM head is MLM-shaped
(its ``transform`` dense block): pointing this script at a saved head
artifact quantizes it through the identical per-channel path.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flax import serialization  # noqa: E402

from pdnlp_tpu.serve.quant import (  # noqa: E402
    is_quantized, quant_error_report, quantize_params,
)
from pdnlp_tpu.train import checkpoint as ckpt  # noqa: E402


def emit_kv_scales(params, model: str, checkpoint: str) -> str:
    """Calibrate + publish the int8 KV scale tables for ``checkpoint``
    (sidecar ``<stem>.kvscales.msgpack``, manifest-verified)."""
    import numpy as np

    from pdnlp_tpu.models import get_config
    from pdnlp_tpu.models.decoder import calibrate_kv_scales

    vocab = int(np.asarray(params["embeddings"]["word"]).shape[0])
    cfg = get_config(model, vocab_size=vocab)
    k_scale, v_scale = calibrate_kv_scales(params, cfg)
    out = checkpoint.rsplit(".msgpack", 1)[0] + ".kvscales.msgpack"
    ckpt.publish(out, serialization.to_bytes(
        {"k_scale": k_scale, "v_scale": v_scale}))
    print(f"wrote {out}  (KV scale tables {k_scale.shape}, model={model}, "
          f"vocab={vocab})")
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("checkpoint", help="params checkpoint (.msgpack)")
    p.add_argument("-o", "--output", default=None,
                   help="artifact path (default: <checkpoint>.int8.msgpack)")
    p.add_argument("--kv_calib", default=None, metavar="MODEL",
                   help="also emit int8 KV-cache scale tables for this "
                        "registry model (generative decode, --kv_dtype "
                        "int8); runs a seeded synthetic causal forward — "
                        "no data, CPU is fine")
    ns = p.parse_args(argv)

    params = ckpt.load_raw(ns.checkpoint)
    if is_quantized(params):
        print(f"{ns.checkpoint} is already an int8 artifact", file=sys.stderr)
        return 1
    if ns.kv_calib:
        emit_kv_scales(params, ns.kv_calib, ns.checkpoint)
    qparams = quantize_params(params)
    report = quant_error_report(params, qparams)
    if not report:
        print(f"{ns.checkpoint}: no dense blocks found — not a params "
              "checkpoint?", file=sys.stderr)
        return 1

    out = ns.output or (ns.checkpoint.rsplit(".msgpack", 1)[0]
                        + ".int8.msgpack")
    # crash-atomic + checksum manifest, like every other published
    # checkpoint — a truncated artifact then fails loudly at load time
    # instead of three layers later as an opaque msgpack error
    ckpt.publish(out, serialization.to_bytes(qparams))

    in_bytes = os.path.getsize(ns.checkpoint)
    print(f"wrote {out}  ({in_bytes / 1e6:.1f} MB -> "
          f"{os.path.getsize(out) / 1e6:.1f} MB)")
    print(f"{'block':<28} {'max|dW|':>10} {'rel':>8}")
    for path, (err, rel) in sorted(report.items()):
        print(f"{path:<28} {err:>10.2e} {rel:>8.2%}")
    worst = max(rel for _, rel in report.values())
    print(f"worst per-block relative error: {worst:.2%} "
          "(symmetric per-channel int8 bound: <= 1/127 of the channel amax)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
