#!/usr/bin/env python
"""Offline int8 weight quantization of a committed checkpoint.

Produces the serving artifact ``--serve_dtype int8`` can load directly:
every dense block's kernel stored as per-channel symmetric int8 + one fp32
scale per output channel (``pdnlp_tpu.serve.quant`` — the identical math
the engine applies when quantizing a float checkpoint on the fly, so the
two routes can never disagree).  Calibration is weight-only: no data, no
device — this runs anywhere the checkpoint file does.

    python scripts/quantize_ckpt.py output/dp-cls.msgpack
    # -> output/dp-cls.int8.msgpack + a per-block error report

    python serve_tpu.py --serve_dtype int8 --ckpt output/dp-cls.int8.msgpack
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flax import serialization  # noqa: E402

from pdnlp_tpu.serve.quant import (  # noqa: E402
    is_quantized, quant_error_report, quantize_params,
)
from pdnlp_tpu.train import checkpoint as ckpt  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("checkpoint", help="params checkpoint (.msgpack)")
    p.add_argument("-o", "--output", default=None,
                   help="artifact path (default: <checkpoint>.int8.msgpack)")
    ns = p.parse_args(argv)

    params = ckpt.load_raw(ns.checkpoint)
    if is_quantized(params):
        print(f"{ns.checkpoint} is already an int8 artifact", file=sys.stderr)
        return 1
    qparams = quantize_params(params)
    report = quant_error_report(params, qparams)
    if not report:
        print(f"{ns.checkpoint}: no dense blocks found — not a params "
              "checkpoint?", file=sys.stderr)
        return 1

    out = ns.output or (ns.checkpoint.rsplit(".msgpack", 1)[0]
                        + ".int8.msgpack")
    # crash-atomic + checksum manifest, like every other published
    # checkpoint — a truncated artifact then fails loudly at load time
    # instead of three layers later as an opaque msgpack error
    ckpt.publish(out, serialization.to_bytes(qparams))

    in_bytes = os.path.getsize(ns.checkpoint)
    print(f"wrote {out}  ({in_bytes / 1e6:.1f} MB -> "
          f"{os.path.getsize(out) / 1e6:.1f} MB)")
    print(f"{'block':<28} {'max|dW|':>10} {'rel':>8}")
    for path, (err, rel) in sorted(report.items()):
        print(f"{path:<28} {err:>10.2e} {rel:>8.2%}")
    worst = max(rel for _, rel in report.values())
    print(f"worst per-block relative error: {worst:.2%} "
          "(symmetric per-channel int8 bound: <= 1/127 of the channel amax)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
