#!/usr/bin/env python
"""Perf probe: isolate device step time vs host/data/transfer time.

Times three loops over N steps of the benched config (dp, bf16, batch 32):
  a) device-only: one pre-transferred batch re-fed every step;
  b) +transfer:   one pre-collated host batch, put() every step;
  c) full loop:   real loader (cached encodings) + put() every step.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

jax.config.update("jax_compilation_cache_dir", "output/xla_cache")

from pdnlp_tpu.train.run import build_parallel_trainer
from pdnlp_tpu.utils.config import Args

N = 100

args = Args(strategy="dp", dtype="bfloat16", dev=True, log_every=10**9)
trainer, train_loader, dev_loader = build_parallel_trainer(args, mode="dp")
host_batch = next(iter(train_loader))
dev_batch = trainer.put(host_batch)
trainer.train_step.lower(trainer.state, dev_batch).compile()

def finish(metrics):
    float(jax.device_get(metrics["loss"]))

# warmup
state = trainer.state
for _ in range(3):
    state, m = trainer.train_step(state, dev_batch)
finish(m)

t0 = time.time()
for _ in range(N):
    state, m = trainer.train_step(state, dev_batch)
finish(m)
t_dev = time.time() - t0

t0 = time.time()
for _ in range(N):
    state, m = trainer.train_step(state, trainer.put(host_batch))
finish(m)
t_put = time.time() - t0

t0 = time.time()
it = iter(train_loader)
n_full = 0
for batch in it:
    state, m = trainer.train_step(state, trainer.put(batch))
    n_full += 1
    if n_full == N:
        break
finish(m)
t_full = time.time() - t0

# dispatch-only cost: how long does enqueueing N steps take (no barrier)?
t0 = time.time()
for _ in range(N):
    state, m = trainer.train_step(state, dev_batch)
t_enq = time.time() - t0  # jaxlint: disable=R4 — the no-barrier delta IS the measurement here
finish(m)

flops_step = 6 * 85.6e6 * (32 * 128) + 12 * 2 * 2 * 32 * 12 * 128 * 128 * 64 * 3
print(f"device-only : {t_dev/N*1e3:8.2f} ms/step  ({N/t_dev:6.1f} steps/s)")
print(f"+put()      : {t_put/N*1e3:8.2f} ms/step  ({N/t_put:6.1f} steps/s)")
print(f"full loader : {t_full/n_full*1e3:8.2f} ms/step  ({n_full/t_full:6.1f} steps/s)")
print(f"enqueue-only: {t_enq/N*1e3:8.2f} ms/step (host dispatch cost)")
print(f"approx MFU at device-only: {flops_step/(t_dev/N)/197e12*100:.1f}% (v5e bf16 peak 197 TF/s)")
