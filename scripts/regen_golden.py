#!/usr/bin/env python
"""Regenerate tests/assets/golden_trace.json on the 8-device CPU mesh.

Run ONLY for deliberate, documented training-math changes (the asset pins
init, data order, masking, dropout streams, loss math, and the optimizer).

    python scripts/regen_golden.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import json

from pdnlp_tpu.train.run import build_parallel_trainer
from pdnlp_tpu.utils.config import Args

ASSET = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tests", "assets", "golden_trace.json")

# rng_impl pinned to threefry2x32: the golden contract is "stable numbers
# unless training math changes", and only threefry streams are stable across
# backends/XLA versions (rbg — the perf default — explicitly is not).
CONFIG = {"model": "bert-tiny", "max_seq_len": 64, "train_batch_size": 16,
          "data_limit": 2000, "dtype": "float32", "seed": 123,
          "rng_impl": "threefry2x32",
          "mesh": "dp over 8 virtual CPU devices", "steps": 30}


MODES_ASSET = os.path.join(os.path.dirname(ASSET), "golden_modes.json")
MODE_STEPS = 10


def main():
    args = Args(model=CONFIG["model"], max_seq_len=CONFIG["max_seq_len"],
                train_batch_size=CONFIG["train_batch_size"],
                data_limit=CONFIG["data_limit"], dtype=CONFIG["dtype"],
                seed=CONFIG["seed"], rng_impl=CONFIG["rng_impl"],
                log_every=10 ** 9)
    trainer, loader, _ = build_parallel_trainer(args, mode="dp")
    losses, epoch = [], 0
    while len(losses) < CONFIG["steps"]:
        loader.set_epoch(epoch)
        for b in loader:
            trainer.state, m = trainer.train_step(trainer.state, trainer.put(b))
            losses.append(round(float(m["loss"]), 8))
            if len(losses) == CONFIG["steps"]:
                break
        epoch += 1
    with open(ASSET, "w") as f:
        json.dump({"config": CONFIG, "losses": losses}, f, indent=2)
    print(f"wrote {ASSET}")
    print(losses[:5], "...")


def regen_modes():
    """10-step traces for EVERY sharding path (tests/golden_modes.py owns
    the builders, so the regen and the test can never drift)."""
    from tests.golden_modes import MODES, trace

    out = {}
    for mode in MODES:
        losses = [round(x, 8) for x in trace(mode, MODE_STEPS)]
        out[mode] = {"steps": MODE_STEPS, "losses": losses}
        print(f"{mode}: {losses[:3]} ...")
    with open(MODES_ASSET, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {MODES_ASSET}")


if __name__ == "__main__":
    main()
    regen_modes()
