#!/usr/bin/env python
"""Long-context TRAINING measurements (VERDICT r4 item 4).

Runs real fused train steps on ``bert-base-long`` (2048-position table) at
seq 1024/2048 on the chip — remat on, bf16, XLA vs the pallas flash kernel —
and records steps/s, tokens/s, and peak HBM.  This is the full-step number
the op-level flash table (README) could not give: the crossover claim for
training comes from here.

Writes/merges ``results/longcontext.json``.

    python scripts/bench_longcontext.py [name-substring ...]
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PATH = os.path.join(REPO, "results", "longcontext.json")

CODE = r"""
import json, sys, time
spec = json.loads(sys.argv[1])
import jax, jax.numpy as jnp
jax.config.update('jax_compilation_cache_dir', 'output/xla_cache')
from pdnlp_tpu.train.run import build_parallel_trainer
from pdnlp_tpu.utils.config import Args
args = Args(**spec['args'])
tr, tl, _ = build_parallel_trainer(args, mode='dp')
batch = tr.put(next(iter(tl)))
state = jax.tree_util.tree_map(jnp.copy, tr.state)
for _ in range(3):
    state, m = tr.train_step(state, batch)
float(jax.device_get(m['loss']))
n = spec.get('steps', 20)
t0 = time.time()
for _ in range(n):
    state, m = tr.train_step(state, batch)
float(jax.device_get(m['loss']))
dt = time.time() - t0
stats = jax.devices()[0].memory_stats() or {}
print(json.dumps({
    'steps_per_sec': round(n / dt, 3),
    'tokens_per_sec': round(n / dt * args.train_batch_size * args.max_seq_len),
    'peak_hbm_gb': round(stats.get('peak_bytes_in_use', 0) / 2**30, 2),
    'loss': round(float(jax.device_get(m['loss'])), 4),
}))
"""


def run(name, seq, batch, attn, remat=True, extra=None):
    # attn_dropout=0 on EVERY row: probability dropout forces the XLA
    # attention path (ops/attention.py), so a "pallas" row with the default
    # 0.1 would silently measure XLA — and the xla/flash comparison must
    # train the same model anyway
    args = dict(strategy="dp", model="bert-base-long", dtype="bfloat16",
                max_seq_len=seq, train_batch_size=batch, dev_batch_size=batch,
                remat=remat, attention_impl=attn, log_every=10 ** 9,
                data_limit=2000, attn_dropout=0.0)
    args.update(extra or {})
    out = subprocess.run(
        [sys.executable, "-c", CODE,
         json.dumps({"args": args, "steps": 20})],
        capture_output=True, text=True, cwd=REPO)
    if out.returncode != 0:
        print(f"{name}: FAILED\n{out.stderr[-2500:]}", file=sys.stderr)
        return {"error": out.stderr.strip().splitlines()[-1][:300]
                if out.stderr.strip() else "unknown"}
    r = json.loads(out.stdout.strip().splitlines()[-1])
    r["config"] = {"seq": seq, "batch": batch, "attention_impl": attn,
                   "remat": remat, **(extra or {})}
    print(f"{name}: {r['steps_per_sec']} steps/s, {r['tokens_per_sec']} tok/s,"
          f" peak {r['peak_hbm_gb']} GB", file=sys.stderr)
    return r


def _dump(res, path=PATH):
    """Atomic artifact write: an interrupt mid-dump must not eat the
    previously measured (minutes-of-chip-time) rows."""
    tmp = path + ".tmp"
    json.dump(res, open(tmp, "w"), indent=2)
    os.replace(tmp, path)


def merge_rows(new_rows, path=PATH, device=None):
    """Merge freshly measured rows into ``results/longcontext.json``
    WITHOUT clobbering history: an existing row without an ``"error"``
    key is never overwritten (the committed v5e rows are minutes of chip
    time; a CPU smoke re-run must not eat them) — only error rows and
    new names take the incoming value.  ``meta.device`` is only stamped
    when absent, for the same reason.  Returns the merged dict (also
    written to ``path``) and the list of row names actually merged —
    ``bench.py --longcontext`` funnels its smoke rows through here, and
    the non-clobber property is pinned by ``tests/test_longcontext.py``.
    """
    res = json.load(open(path)) if os.path.exists(path) else {}
    res.setdefault("meta", {})
    res.setdefault("rows", {})
    merged = []
    for name, row in new_rows.items():
        old = res["rows"].get(name)
        if old is not None and "error" not in old:
            continue  # history wins
        res["rows"][name] = row
        merged.append(name)
    if device and "device" not in res["meta"]:
        res["meta"]["device"] = device
    _dump(res, path)
    return res, merged


def main():
    res = json.load(open(PATH)) if os.path.exists(PATH) else {}
    res.setdefault("meta", {
        "model": "bert-base-long (2048-position table, models/config.py)",
        "protocol": "20 re-fed fused train steps (fwd+bwd+AdamW) after 3 "
                    "warmup, bf16, remat on, single chip; tokens/s = "
                    "steps/s * batch * seq",
    })
    res.setdefault("rows", {})
    grid = {
        "seq512_b16_xla": (512, 16, "xla"),
        "seq512_b16_flash": (512, 16, "pallas"),
        "seq1024_b8_xla": (1024, 8, "xla"),
        "seq1024_b8_flash": (1024, 8, "pallas"),
        "seq2048_b4_xla": (2048, 4, "xla"),
        "seq2048_b4_flash": (2048, 4, "pallas"),
        "seq2048_b4_xla_noremat": (2048, 4, "xla", False),
        # the r5 headline's activation lever at long sequence: GELU share
        # of the step shrinks as O(S^2) attention grows, so the gain
        # should taper vs the +7% measured at seq 128
        "seq1024_b8_xla_tanh": (1024, 8, "xla", True, {"gelu": "tanh"}),
        "seq2048_b4_xla_tanh": (2048, 4, "xla", True, {"gelu": "tanh"}),
    }
    # space- or comma-separated substrings; a token that exactly names a
    # row selects ONLY that row (so "seq1024_b8_xla" can't silently drag
    # in its "_tanh" substring-superset sibling)
    only = [t for a in sys.argv[1:] for t in a.split(",") if t]

    def selected(name):
        if not only:
            return True
        if any(o == name for o in only):
            return True
        return any(o in name and o not in grid for o in only)

    for name, spec in grid.items():
        if not selected(name):
            continue
        if name in res["rows"] and "error" not in res["rows"][name]:
            continue
        row = run(name, *spec)
        if "error" in row:
            # one retry: first-touch chip init / compile-cache races are
            # the observed transient class; a second error is real
            print(f"{name}: retrying once after error", file=sys.stderr)
            row = run(name, *spec)
        res["rows"][name] = row
        _dump(res)

    # the sequence-parallel path at 1024: the sp entrypoint itself (ring
    # attention inside shard_map; seq axis 1 on the one-chip image — the
    # ring's multi-shard parity is pinned by tests/test_sp.py and the
    # cross-process spawn test), probe = the controlled metric
    name = "sp_seq1024_b8_ring"
    if selected(name) and (
            name not in res["rows"] or "error" in res["rows"][name]):
        import re

        argv = [sys.executable, "multi-tpu-sp-cls.py", "--model",
                "bert-base-long", "--max_seq_len", "1024",
                "--train_batch_size", "8", "--dev_batch_size", "8",
                "--dtype", "bfloat16", "--attn_dropout", "0.0",
                "--data_limit", "2000", "--remat", "true",
                "--warmup_compile", "true", "--probe_steps", "20",
                "--log_every", "1000000"]
        out = subprocess.run(argv, capture_output=True, text=True, cwd=REPO)
        text = out.stdout + out.stderr
        probe = re.findall(r"probe steps/s：([\d.]+)", text)
        mins = re.findall(r"耗时：([\d.]+)分钟", text)
        row = ({"steps_per_sec": float(probe[-1]),
                "tokens_per_sec": round(float(probe[-1]) * 8 * 1024),
                "epoch_minutes": float(mins[-1]) if mins else None,
                "config": {"seq": 1024, "batch": 8, "impl": "ring(shard_map)",
                           "remat": True, "argv": argv[1:]}}
               if out.returncode == 0 and probe else
               {"error": text.strip().splitlines()[-1][:300]})
        res["rows"][name] = row
        print(f"{name}: {row}", file=sys.stderr)

    try:
        import jax

        res["meta"]["device"] = jax.devices()[0].device_kind
    except Exception:
        pass
    _dump(res)
    print(json.dumps(res["rows"], indent=2))


if __name__ == "__main__":
    main()
