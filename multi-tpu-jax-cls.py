"""Mesh data-parallel training — the DDP analog and the benchmark's
north-star entrypoint (``BASELINE.json``).

Capability twin of ``/root/reference/multi-gpu-distributed-cls.py``:
``dist.init_process_group`` -> ``jax.distributed`` rendezvous (env vars or
``--coordinator_address``); ``DistributedSampler`` -> per-host dataset shard
feeding one global device-sharded ``jax.Array``; DDP's NCCL gradient
all-reduce -> XLA ICI all-reduce inserted from sharding annotations; the
``loss_reduce``/``output_reduce`` collectives (``:139-155``) happen inside
the jitted step.  Steps per epoch shrink with the data axis (288 single ->
144 @ 2-way), matching the reference's step math.

Run (single host, all chips):   python multi-tpu-jax-cls.py
Multi-host (one process each):  python multi-tpu-jax-cls.py \
    --coordinator_address host0:8476 --num_processes 2 --process_id $RANK
The AMP-analog north-star config is ``--dtype bfloat16``.
"""
from pdnlp_tpu.train.run import run_parallel
from pdnlp_tpu.utils.config import Args, parse_cli

if __name__ == "__main__":
    run_parallel(parse_cli(base=Args(strategy="dp")), mode="dp")
