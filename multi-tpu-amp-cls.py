"""Mesh data-parallel + bf16 — the DDP+AMP analog (the reference's fastest
hand-rolled config, 0.6336 min, ``/root/reference/README.md:16``).

Capability twin of ``/root/reference/multi-gpu-distributed-mp-amp-cls.py:
160-175``: ``autocast`` becomes bf16 compute on the MXU (master params stay
fp32; softmax/LayerNorm reduce fp32) and the dynamic ``GradScaler`` is
**deleted, not ported** — bf16 carries fp32's exponent range, so nothing
underflows and no loss scaling is needed (see ``train/precision.py``).
The reference's known quirk of never calling ``zero_grad`` in this script
(``:168-181``) is documented, not replicated — grads here are fresh by
construction (``jax.grad`` is functional).

    python multi-tpu-amp-cls.py
"""
from pdnlp_tpu.train.run import run_parallel
from pdnlp_tpu.utils.config import Args, parse_cli

if __name__ == "__main__":
    run_parallel(parse_cli(base=Args(strategy="amp", dtype="bfloat16")), mode="dp")
